"""Chief-side recovery plane: the policy layer that makes detectors ACT.

PRs 5/8/11 built the detection substrate — the PS watchdog flags stalls
(``ps.anomaly.*``), the health monitors flag sick numerics
(``health.anomaly``), the alert engine fires on drift, and the flight
recorder snapshots the evidence — but until this module the only actions
anywhere were warn, record and halt, and the coordinator hard-killed the
chief on any worker exit (the reference's fail-fast ``coordinator.py:98-110``
faithfully reproduced). At pod scale machine loss is routine, not
exceptional (Scale MLPerf-0.6 pods, arXiv 1909.09756); this module closes
the detect→act loop:

- **Auto-eviction** (:func:`evict`): the watchdog retires a worker whose
  stall outlasts ``AUTODIST_EVICT_AFTER_S`` from the staleness gate, so the
  live workers resume instead of parking at the bound forever. The evicted
  worker's parked gate RPC fails typed
  (:class:`~autodist_tpu.parallel.staleness.WorkerEvicted`), and the client
  auto-rejoins — a wrongly-evicted victim recovers on its own.
- **Rejoin bookkeeping** (:func:`log_rejoin`): a replacement (or wrongly
  evicted) worker re-registers, seeded at the slowest live step count, and
  catches up on the chief's LIVE params over ``read_min`` — checkpoint-free.
- **Rollback** (:class:`SnapshotRing` + :func:`rollback`): under
  ``AUTODIST_HEALTH_ACTION=recover`` (or ``AUTODIST_ALERT_ACTION=recover``)
  ``train()`` keeps a bounded in-memory ring of last-known-good states taken
  at health-clean log boundaries; an anomaly rolls back to the newest good
  one and resumes, bounded by ``AUTODIST_RECOVER_MAX`` attempts before
  escalating to the existing halt.
- **Respawn backoff** (:func:`backoff_s`): the coordinator's
  ``AUTODIST_WORKER_FAILURE=respawn`` policy relaunches a dead worker with
  bounded exponential backoff instead of ``os._exit(1)``.

Everything the plane DOES is booked: ``recover.{evicted,rejoined,rollback,
respawn}`` counters + structured events in the shared registry, a bounded
in-process :func:`recovery_snapshot` the ``status`` opcode ships (rendered
by ``adtop``/``adfleet``), and flight-recorder snapshots through the
debounce. The module is deliberately jax-free and import-light — policy,
not mechanism; the gate/transport/train loop own the mechanisms.
"""

import collections
import random
import threading
import time
from typing import Any, Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["SnapshotRing", "evict", "rollback", "backoff_s",
           "log_eviction", "log_rejoin", "log_rollback", "log_respawn",
           "recovery_snapshot", "reset"]

# Bounded per-category record retention in the in-process log (the status
# opcode ships these; counts are unbounded counters).
KEEP_RECORDS = 16

# Membership eviction categories: "stall" = the watchdog's autonomous act,
# "disconnect" = the transport observed the worker's socket die (crash OR
# clean close — indistinguishable at the server, both retire the slot).
EVICT_KINDS = ("stall", "disconnect")


def _counter(name: str):
    from autodist_tpu.telemetry import metrics as _metrics
    return _metrics.counter(name)


def _event(name: str, **fields):
    from autodist_tpu.telemetry import metrics as _metrics
    _metrics.event(name, **fields)


class _RecoveryLog:
    """Process-global, lock-guarded record of every recovery action — the
    ``recovery`` section of the ``status`` opcode. Bounded deques per
    category; total counts survive the deque bound."""

    def __init__(self):
        self._lock = san_lock()
        self._evictions = collections.deque(maxlen=KEEP_RECORDS)
        self._rejoins = collections.deque(maxlen=KEEP_RECORDS)
        self._rollbacks = collections.deque(maxlen=KEEP_RECORDS)
        self._respawns = collections.deque(maxlen=KEEP_RECORDS)
        self._counts = {"evicted": 0, "rejoined": 0, "rollbacks": 0,
                        "respawns": 0}
        # Per-worker membership generation as LAST observed by this plane
        # (the staleness gate's occupancy generation at the worker's most
        # recent rejoin) — the status section's membership fingerprint.
        self._generations: Dict[int, int] = {}

    def add(self, category: str, dq_name: str, record: Dict[str, Any]):
        record = dict(record, t_wall_s=round(time.time(), 3))
        with self._lock:
            getattr(self, dq_name).append(record)
            self._counts[category] += 1
        return record

    def note_generation(self, worker_id, generation: int):
        # PS gate slots use numeric ids; the serving fleet router books its
        # replicas by "host:port". Normalize int-able ids (so PS records
        # keep their historical int keys) and keep the rest as strings.
        try:
            worker_id = int(worker_id)
        except (TypeError, ValueError):
            worker_id = str(worker_id)
        with self._lock:
            self._generations[worker_id] = int(generation)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"evictions": list(self._evictions),
                    "rejoins": list(self._rejoins),
                    "rollbacks": list(self._rollbacks),
                    "respawns": list(self._respawns),
                    "counts": dict(self._counts),
                    "generations": dict(sorted(self._generations.items(),
                                               key=lambda kv: str(kv[0])))}


_LOG = _RecoveryLog()


def reset():
    """Fresh log (tests only — the log is additive for the process's life)."""
    global _LOG
    _LOG = _RecoveryLog()


def recovery_snapshot() -> Dict[str, Any]:
    """The ``status`` opcode's ``recovery`` section: bounded recent records
    per category, total counts, per-worker membership generations. A stable
    empty shell when nothing ever acted — pollers keep one schema."""
    return _LOG.snapshot()


# ---------------------------------------------------------------- bookkeeping

def log_eviction(worker_id, kind: str = "stall",
                 age_s: Optional[float] = None) -> Dict[str, Any]:
    """Book one gate eviction (counter + bounded record; watchdog-driven
    ``stall`` evictions additionally emit a ``recover.evicted`` event —
    disconnect retires fire on every clean teardown too, and an event per
    normal close would drown the ring)."""
    _counter("recover.evicted").inc()
    rec = {"worker": worker_id, "kind": kind}
    if age_s is not None:
        rec["age_s"] = round(float(age_s), 3)
    rec = _LOG.add("evicted", "_evictions", rec)
    if kind == "stall":
        _event("recover.evicted", **{k: v for k, v in rec.items()
                                     if k != "t_wall_s"})
    return rec


def log_rejoin(worker_id, generation: int,
               seeded_step: Optional[int] = None) -> Dict[str, Any]:
    """Book one membership rejoin (a previously-retired slot re-registered,
    seeded at the slowest live step count)."""
    _counter("recover.rejoined").inc()
    _LOG.note_generation(worker_id, generation)
    rec = {"worker": worker_id, "generation": int(generation)}
    if seeded_step is not None:
        rec["seeded_step"] = int(seeded_step)
    rec = _LOG.add("rejoined", "_rejoins", rec)
    _event("recover.rejoined", **{k: v for k, v in rec.items()
                                  if k != "t_wall_s"})
    return rec


def log_rollback(from_step, to_step: int, attempt: int) -> Dict[str, Any]:
    """Book one recover-action rollback (bad state discarded, last-known-good
    re-adopted)."""
    _counter("recover.rollback").inc()
    rec = _LOG.add("rollbacks", "_rollbacks",
                   {"from_step": from_step, "to_step": int(to_step),
                    "attempt": int(attempt)})
    _event("recover.rollback", **{k: v for k, v in rec.items()
                                  if k != "t_wall_s"})
    return rec


def log_respawn(address: str, attempt: int,
                backoff: float) -> Dict[str, Any]:
    """Book one coordinator worker respawn."""
    _counter("recover.respawn").inc()
    rec = _LOG.add("respawns", "_respawns",
                   {"address": str(address), "attempt": int(attempt),
                    "backoff_s": round(float(backoff), 3)})
    _event("recover.respawn", **{k: v for k, v in rec.items()
                                 if k != "t_wall_s"})
    return rec


# -------------------------------------------------------------------- actions

def backoff_s(attempt: int, base_s: float, cap_s: float = 30.0) -> float:
    """Jittered bounded exponential backoff: ``min(cap, base * 2^attempt)``
    scaled by a uniform [0.5, 1.0) jitter so a fleet of retriers never
    thunders in lockstep. Always <= ``cap_s`` (bounded — GL005's spirit)."""
    if base_s <= 0.0:
        return 0.0
    return min(float(cap_s), float(base_s) * (2.0 ** max(0, int(attempt)))) \
        * random.uniform(0.5, 1.0)


def evict_after_s() -> Optional[float]:
    """The armed auto-eviction threshold, or None when the policy is off
    (``AUTODIST_EVICT_AFTER_S`` unset/0 — detection stays warn-only)."""
    val = float(const.ENV.AUTODIST_EVICT_AFTER_S.val)
    return val if val > 0.0 else None


def evict(controller, worker_id, kind: str = "stall",
          age_s: Optional[float] = None, server=None) -> Dict[str, Any]:
    """Retire ``worker_id`` from the staleness gate NOW and book the act:
    the frozen step count stops pinning ``min(steps)`` (live workers parked
    at the bound resume), the worker's own parked gate RPC fails typed
    (``WorkerEvicted`` — the client's cue to rejoin), and an armed flight
    recorder snapshots the moment through its debounce.

    The retire is unconditional (no generation token): the eviction evidence
    is seconds of silence, and the tiny race against a concurrent re-register
    self-heals — the evicted client's next gate call raises ``WorkerEvicted``
    and it rejoins automatically. Returns the booked record, or None when
    the worker was already retired (nothing to book — counts track gate
    ACTIONS, never no-ops)."""
    if not controller.retire(worker_id):
        logging.info("recover: worker %s already retired; eviction is a "
                     "no-op", worker_id)
        return None
    rec = log_eviction(worker_id, kind=kind, age_s=age_s)
    logging.warning(
        "recover: EVICTED worker %s from the staleness gate (%s%s) — live "
        "workers resume; the worker may rejoin via register", worker_id,
        kind, f", silent {age_s:.1f}s" if age_s is not None else "")
    from autodist_tpu.telemetry import recorder as _recorder
    _recorder.maybe_record(f"recover.evict.w{worker_id}", server=server)
    return rec


class SnapshotRing:
    """Bounded in-memory ring of last-known-good ``(step, state)`` pairs.

    ``train()`` pushes at every log boundary that closed HEALTHY (no anomaly
    raised, no alert fired). ``copy_fn`` is applied to each pushed state —
    the SYNC runner's step DONATES its input state buffers, so a bare
    reference would be deleted by the very next dispatch; ``train()``
    supplies a fused on-device copy (a jitted ``tree_map(jnp.copy)``), kept
    out of this module so the recovery plane stays jax-free. ``keep`` bounds
    the pinned device memory to K extra states; the default 2 keeps one
    boundary of slack for a SLOW-BURN anomaly — when a rollback to the
    newest snapshot fails again at the same incident, :func:`rollback`
    calls :meth:`drop_newest` and the retry lands one boundary deeper.
    Single-threaded by contract (the train loop is the only caller)."""

    DEFAULT_KEEP = 2

    def __init__(self, keep: int = DEFAULT_KEEP, copy_fn=None):
        self.keep = max(1, int(keep))
        self._copy = copy_fn
        self._ring: List[Any] = []   # (step, state), oldest first

    def push(self, step: int, state):
        if self._copy is not None:
            state = self._copy(state)
        if self._ring and self._ring[-1][0] == step:
            self._ring[-1] = (step, state)   # boundary replayed post-rollback
            return
        self._ring.append((int(step), state))
        del self._ring[:max(0, len(self._ring) - self.keep)]

    def newest(self):
        """``(step, state)`` of the newest good snapshot, or None."""
        return self._ring[-1] if self._ring else None

    def checkout(self):
        """``(step, state)`` of the newest good snapshot with the state
        COPIED back out (``copy_fn`` again) — the resumed loop donates the
        buffers it is handed, and a second rollback to the same snapshot
        must find the ring entry alive, not donated. None when empty."""
        if not self._ring:
            return None
        step, state = self._ring[-1]
        return (step, self._copy(state) if self._copy is not None else state)

    def states(self) -> List[Any]:
        """The retained snapshot states, oldest first — the memory plane's
        census input (``memplane.tag("snapshots", ring.states())``): the
        ring's deep copies are pinned device memory no other owner claims."""
        return [state for _, state in self._ring]

    def drop_newest(self):
        """Discard the newest snapshot — it was rolled back to and the SAME
        incident fired again, so it is suspect (a slow-burn anomaly already
        latent at capture time); the next checkout lands one boundary
        deeper. An empty ring afterwards means escalation."""
        if self._ring:
            self._ring.pop()

    def __len__(self) -> int:
        return len(self._ring)


def recover_max() -> int:
    """The rollback/respawn attempt budget (``AUTODIST_RECOVER_MAX``)."""
    return max(1, int(const.ENV.AUTODIST_RECOVER_MAX.val))


def rollback(exc, ring: Optional[SnapshotRing], attempt: int,
             max_attempts: int, runner=None):
    """One recover-action rollback: return the newest good state (re-seeding
    an async runner's parameter service through ``runner.restore``), or
    ESCALATE to the existing halt when the attempt budget is spent or no
    good snapshot exists.

    ``exc`` is the signal that interrupted the run (``HealthRecover`` or
    ``AlertRecover``); escalation re-raises it as the exact halt type the
    halt action would have produced, live state attached — recover degrades
    to halt, never to silence."""
    from autodist_tpu.telemetry import health as _health
    from autodist_tpu.telemetry import recorder as _recorder
    if ring is not None and attempt > 1:
        # Same-incident retry: the newest snapshot was already resumed from
        # and the anomaly re-fired — a slow-burn corruption may predate it,
        # so fall back one boundary deeper instead of replaying it forever.
        ring.drop_newest()
    good = ring.checkout() if ring is not None else None
    from_step = getattr(exc, "step", None)
    if good is None or attempt > max_attempts:
        reason = ("no healthy snapshot in the ring" if good is None else
                  f"attempt {attempt} exceeds AUTODIST_RECOVER_MAX="
                  f"{max_attempts}")
        logging.error("recover: cannot roll back (%s) — escalating to halt",
                      reason)
        if isinstance(exc, _health.HealthRecover):
            raise _health.HealthHalt(exc.step, exc.state,
                                     exc.anomalies) from exc
        raise exc
    to_step, state = good
    log_rollback(from_step, to_step, attempt)
    logging.warning(
        "recover: rolling back from step %s to last-known-good step %d "
        "(attempt %d/%d) and resuming", from_step, to_step, attempt,
        max_attempts)
    # Snapshot the evidence (the bad state is still live on `exc`) through
    # the debounce — an anomaly storm mid-recovery costs one dir per window.
    _recorder.maybe_record(f"recover.rollback.s{to_step}")
    # Async-PS regimes: the parameter service owns the state — re-seed it
    # explicitly (the sync runner adopts the returned state on its next run).
    restore = getattr(runner, "restore", None)
    if callable(restore) and getattr(runner, "service", None) is not None:
        restore(state)
    return state
