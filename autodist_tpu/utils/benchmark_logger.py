"""Structured benchmark logging.

Counterpart of the reference's benchmark logging stack
(``examples/benchmark/utils/logs/logger.py:108-223``: ``BaseBenchmarkLogger`` /
``BenchmarkFileLogger`` / ``BenchmarkBigQueryLogger``, and
``utils/logs/mlperf_helper.py``'s compliance tags). Promoted into the framework so
every example/benchmark shares one implementation (the reference kept it under
examples).

- :class:`BaseBenchmarkLogger` prints structured metrics through the framework
  logger.
- :class:`BenchmarkFileLogger` appends one JSON object per line to
  ``metric.log`` / ``benchmark_run.log`` under a directory (the reference's file
  format: name/value/unit/global_step/timestamp/extras).
- :func:`log_run_info` captures the run's environment (platform, device count,
  jax version, model/dataset/strategy names) like the reference's
  ``gather_run_info``.
- :func:`mlperf_log` emits ``:::MLL``-style compliance lines (reference
  ``mlperf_helper.py`` wrapped the mlperf_compliance package; the tag format here
  follows the public MLPerf logging convention so existing scrapers parse it).

The reference's BigQuery sink needs network egress; here any configured
``AUTODIST_BENCHMARK_LOG_DIR`` selects the file sink and the base logger is the
fallback, which is the same graceful degradation the reference used when the
bigquery client was absent.
"""

import json
import os
import time
from typing import Any, Dict, List, Optional

from autodist_tpu.utils import logging

METRIC_LOG_FILE_NAME = "metric.log"
BENCHMARK_RUN_LOG_FILE_NAME = "benchmark_run.log"


class BaseBenchmarkLogger:
    """Log metrics through the framework logger (reference logger.py:108-140)."""

    def log_metric(self, name: str, value: float, unit: Optional[str] = None,
                   global_step: Optional[int] = None,
                   extras: Optional[Dict[str, Any]] = None):
        metric = _metric_dict(name, value, unit, global_step, extras)
        if metric is not None:
            logging.info("Benchmark metric: %s", metric)

    def log_run_info(self, run_info: Dict[str, Any]):
        logging.info("Benchmark run: %s", run_info)

    def log_metrics(self, snapshot: Dict[str, Any],
                    global_step: Optional[int] = None) -> int:
        """Emit a telemetry-registry snapshot (``telemetry.snapshot()``) as
        one metric row per instrument; returns the row count. Histogram
        snapshots (dicts) log their ``count`` as the value with the bucket
        dict riding in ``extras`` — every sink (console, file) inherits this,
        so registry metrics land wherever ordinary metrics do."""
        rows = 0
        for name, value in snapshot.items():
            if isinstance(value, dict):
                self.log_metric(name, value.get("count", 0), unit="count",
                                global_step=global_step, extras=value)
            else:
                self.log_metric(name, value, global_step=global_step)
            rows += 1
        return rows

    def on_finish(self, status: str = "success"):
        pass


class BenchmarkFileLogger(BaseBenchmarkLogger):
    """Append metrics as JSON lines under ``logging_dir``
    (reference logger.py:142-185)."""

    def __init__(self, logging_dir: str):
        self._logging_dir = logging_dir
        os.makedirs(logging_dir, exist_ok=True)
        self._metric_file = open(
            os.path.join(logging_dir, METRIC_LOG_FILE_NAME), "a")

    def log_metric(self, name, value, unit=None, global_step=None, extras=None):
        metric = _metric_dict(name, value, unit, global_step, extras)
        if metric is not None:
            self._metric_file.write(json.dumps(metric, sort_keys=True) + "\n")
            self._metric_file.flush()

    def log_run_info(self, run_info: Dict[str, Any]):
        path = os.path.join(self._logging_dir, BENCHMARK_RUN_LOG_FILE_NAME)
        with open(path, "a") as f:
            f.write(json.dumps(run_info, sort_keys=True, default=str) + "\n")

    def on_finish(self, status: str = "success"):
        self.log_metric("run_status", 1.0 if status == "success" else 0.0,
                        extras={"status": status})
        self._metric_file.close()


def get_benchmark_logger() -> BaseBenchmarkLogger:
    """File logger when AUTODIST_BENCHMARK_LOG_DIR is set, else the base logger
    (the reference selected its sink from flags the same way)."""
    from autodist_tpu import const
    log_dir = const.ENV.AUTODIST_BENCHMARK_LOG_DIR.val
    if log_dir:
        return BenchmarkFileLogger(log_dir)
    return BaseBenchmarkLogger()


def gather_run_info(model_name: str, dataset_name: str = "synthetic",
                    strategy_name: str = "", batch_size: int = 0) -> Dict[str, Any]:
    """Environment + run metadata (reference logger.py:226-260 gathered TF/CUDA
    versions and machine config; here: jax version, platform, device inventory)."""
    import jax
    devices = jax.devices()
    info = {
        "model_name": model_name,
        "dataset": {"name": dataset_name},
        "strategy": strategy_name,
        "batch_size": batch_size,
        "run_date": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "machine_config": {
            "platform": devices[0].platform if devices else "none",
            "num_devices": len(devices),
            "device_kinds": sorted({getattr(d, "device_kind", "?") for d in devices}),
        },
    }
    try:
        info["jax_version"] = jax.__version__
    except AttributeError:
        pass
    return info


def _metric_dict(name, value, unit, global_step, extras) -> Optional[Dict[str, Any]]:
    try:
        value = float(value)
    except (TypeError, ValueError):
        logging.warning("Metric %s has non-numeric value %r; dropped", name, value)
        return None
    import datetime
    ts = datetime.datetime.now(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    return {
        "name": name,
        "value": value,
        "unit": unit,
        "global_step": global_step,
        "timestamp": ts,
        "extras": extras or {},
    }


# ------------------------------------------------------------------- MLPerf

_MLPERF_DEFAULT_VERSION = "4.0.0"


def mlperf_log(key: str, value: Any = None, *, kind: str = "POINT_IN_TIME",
               version: str = _MLPERF_DEFAULT_VERSION,
               out: Optional[List[str]] = None) -> str:
    """Emit one MLPerf-compliance log line (reference mlperf_helper.py wrapped
    mlperf_compliance.mlperf_log; the ``:::MLL`` format is the public convention).

    Returns the formatted line; appends to ``out`` when given, else prints via the
    framework logger at INFO.
    """
    record = {
        "namespace": "",
        "time_ms": int(time.time() * 1000),
        "event_type": kind,
        "key": key,
        "value": value,
        "metadata": {"file": "", "lineno": 0, "mlperf_version": version},
    }
    line = ":::MLL " + json.dumps(record, sort_keys=True, default=str)
    if out is not None:
        out.append(line)
    else:
        logging.info("%s", line)
    return line
