"""ModelSpec — the framework IR. Replaces the reference's GraphItem.

The reference captured a ``tf.Graph`` plus grad↔var pairs via optimizer monkey patches
(``autodist/graph_item.py:73-109,301-317``). In JAX there is no global graph to
capture: the IR is simply *metadata about the parameter pytree* of a user-supplied
train step — name, shape, dtype, and whether the gradient is sparse (embedding-style).
Everything the reference extracted by graph scanning (update-op discovery via op-type
tables, ``graph_item.py:345-419``; IndexedSlices detection, ``:301-317``) falls out of
the functional signature, with sparse-gradient detection done by jaxpr analysis instead
of IndexedSlices typing.
"""

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

PyTree = Any


def _path_name(path) -> str:
    """Render a jax tree path as a stable '/'-joined name.

    These names play the role of the reference's variable names: they key strategy
    NodeConfigs and name checkpoint entries (reference saved under original
    single-node names, ``checkpoint/saver.py:47-61``).
    """
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(str(p.name))
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return "/".join(parts) or "param"


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """Metadata for one trainable parameter (reference: one strategy Node's subject)."""

    name: str
    shape: Tuple[int, ...]
    dtype: Any
    sparse: bool = False        # gradient is row-sparse (reference IndexedSlices)
    trainable: bool = True
    # Batch-leaf name supplying the gather indices for a sparse param (jaxpr
    # provenance analysis). Lets the synchronizer ship (indices, rows) over the
    # wire instead of the dense scatter-add result — the reference's sparse
    # all-gather (all_reduce_synchronizer.py:132-173) knew this from IndexedSlices.
    index_leaf: Optional[str] = None

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def byte_size(self) -> int:
        return self.size * np.dtype(self.dtype).itemsize


class ModelSpec:
    """Parameter-pytree metadata + the original tree structure for round-tripping."""

    def __init__(self, params: PyTree, sparse_names: Sequence[str] = (),
                 trainable_filter: Optional[Callable[[str], bool]] = None):
        leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(params)
        self.treedef = treedef
        self._names: List[str] = []
        self.params: Dict[str, ParamSpec] = {}
        sparse_names = set(sparse_names)
        for path, leaf in leaves_with_paths:
            name = _path_name(path)
            if name in self.params:
                raise ValueError(
                    f"Parameter name collision: two leaves render as {name!r} "
                    f"(names key strategy configs and checkpoints, so they must be unique)")
            shape = tuple(getattr(leaf, "shape", ()) or ())
            dtype = getattr(leaf, "dtype", np.float32)
            trainable = trainable_filter(name) if trainable_filter else True
            self._names.append(name)
            self.params[name] = ParamSpec(
                name=name, shape=shape, dtype=dtype,
                sparse=name in sparse_names, trainable=trainable)

    # --- constructors ---

    @classmethod
    def from_params(cls, params: PyTree, **kwargs) -> "ModelSpec":
        return cls(params, **kwargs)

    @classmethod
    def from_init_fn(cls, init_fn: Callable[..., PyTree], *args, **kwargs) -> "ModelSpec":
        """Build from an initializer without materializing parameters (eval_shape)."""
        shapes = jax.eval_shape(init_fn, *args, **kwargs)
        return cls(shapes)

    @classmethod
    def from_loss_fn(cls, loss_fn: Callable, params: PyTree, *example_args) -> "ModelSpec":
        """Build with automatic sparse-gradient detection.

        The reference learned a gradient was sparse when TF produced ``IndexedSlices``
        (``graph_item.py:301-317``). Here we inspect the jaxpr of ``loss_fn``: a
        parameter consumed **only** by gather/embedding-lookup ops receives row-sparse
        updates, so its PS placement should use the sparse path (Parallax semantics,
        reference ``parallax_strategy.py:38-71``).
        """
        spec = cls(params)
        sparse = set(detect_sparse_params(loss_fn, params, *example_args))
        sources = detect_sparse_index_sources(loss_fn, params, *example_args)
        for name in sparse:
            if name in spec.params:
                spec.params[name] = dataclasses.replace(
                    spec.params[name], sparse=True, index_leaf=sources.get(name))
        return spec

    # --- accessors ---

    @property
    def names(self) -> List[str]:
        return list(self._names)

    @property
    def trainable(self) -> Dict[str, ParamSpec]:
        return {n: p for n, p in self.params.items() if p.trainable}

    def __getitem__(self, name: str) -> ParamSpec:
        return self.params[name]

    def name_to_leaf_index(self) -> Dict[str, int]:
        return {n: i for i, n in enumerate(self._names)}

    def unflatten(self, leaves: Sequence[Any]) -> PyTree:
        return jax.tree_util.tree_unflatten(self.treedef, list(leaves))

    def flatten(self, tree: PyTree) -> List[Any]:
        return jax.tree_util.tree_leaves(tree)

    def __repr__(self):
        return f"ModelSpec({len(self.params)} params, {sum(p.byte_size for p in self.params.values())} bytes)"


# --- sparse-gradient detection by jaxpr analysis ---

_GATHER_PRIMS = {"gather", "take", "dynamic_slice"}


def detect_sparse_params(loss_fn: Callable, params: PyTree, *example_args) -> List[str]:
    """Names of parameters whose only use in ``loss_fn`` is a gather (embedding lookup).

    Best-effort static analysis: traces the forward jaxpr once and tracks, for each
    parameter input var, the primitives that consume it. Parameters consumed solely by
    ``gather``-family primitives get row-sparse gradients (a scatter-add), which the
    PS/Parallax strategies route to the sparse path.
    """
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    names = [_path_name(p) for p, _ in leaves_with_paths]
    leaves = [l for _, l in leaves_with_paths]

    def flat_loss(*flat_params_and_args):
        flat_params = flat_params_and_args[:len(leaves)]
        args = flat_params_and_args[len(leaves):]
        tree = jax.tree_util.tree_unflatten(treedef, list(flat_params))
        return loss_fn(tree, *args)

    try:
        jaxpr = jax.make_jaxpr(flat_loss)(*leaves, *example_args).jaxpr
    except Exception:  # tracing failed (e.g. non-jittable loss) — no detection
        return []

    param_vars = {v: names[i] for i, v in enumerate(jaxpr.invars[:len(leaves)])}
    consumers: Dict[Any, set] = {v: set() for v in param_vars}
    _collect_consumers(jaxpr, consumers)

    out = []
    for v, name in param_vars.items():
        prims = consumers.get(v, set())
        if prims and prims <= _GATHER_PRIMS:
            out.append(name)
    return out


def _is_var(x) -> bool:
    # jaxpr invars may be Literal (unhashable); only track proper Vars.
    return type(x).__name__ == "Var"


# Wrapper primitives whose body we look through: consuming a param via one of these is
# not itself a "use"; the uses are inside the sub-jaxpr (jnp.take lowers to a pjit-of-
# gather, custom_jvp wraps most nn functions).
_TRANSPARENT_PRIMS = {"pjit", "jit", "closed_call", "core_call", "xla_call",
                      "custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr",
                      "remat", "checkpoint", "remat2", "custom_vjp_call_jaxpr"}


def _sub_jaxpr(eqn):
    for param in eqn.params.values():
        inner = getattr(param, "jaxpr", None)
        if inner is not None:
            return inner.jaxpr if hasattr(inner, "jaxpr") else inner
        if type(param).__name__ == "Jaxpr":
            return param
    return None


# Value-preserving primitives: the output holds exactly the input's index values
# (possibly re-laid-out), so provenance flows through unchanged.
_IDX_EXACT_PRIMS = {"broadcast_in_dim", "reshape", "convert_element_type", "squeeze",
                    "copy", "stop_gradient", "transpose", "expand_dims"}


def detect_sparse_index_sources(loss_fn: Callable, params: PyTree,
                                *example_args) -> Dict[str, str]:
    """Map sparse parameter names -> the batch-leaf name providing their gather
    indices, by jaxpr data-flow analysis.

    Walks the forward jaxpr tracking the *origin* of every intermediate: a param
    input, an argument (batch) leaf (with any constant shifts applied to it), or
    unknown. A mapping entry requires EVERY gather of the param to use indices
    that are value-equal to one argument leaf — either directly (through
    reshape/cast-style primitives) or via ``jnp.take``'s negative-index wrap
    ``select_n(idx < 0, idx + dim0, idx)``, whose effect the synchronizer
    reproduces at runtime. Value-transforming index arithmetic (idx+1, idx*2, a
    second differently-indexed gather, clip-mode clamping) disqualifies the param
    — any ambiguity drops the entry and the synchronizer falls back to the dense
    all-reduce wire format, which is always correct.
    """
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    names = [_path_name(p) for p, _ in leaves_with_paths]
    leaves = [l for _, l in leaves_with_paths]
    arg_names: List[str] = []
    arg_leaves: List[Any] = []
    arg_treedefs = []
    for pos, arg in enumerate(example_args):
        lw, td = jax.tree_util.tree_flatten_with_path(arg)
        arg_treedefs.append(td)
        for path, leaf in lw:
            # Single batch arg (the standard session signature) keeps bare names.
            prefix = f"{pos}/" if len(example_args) > 1 else ""
            arg_names.append(prefix + _path_name(path))
            arg_leaves.append(leaf)

    def flat_loss(*flat):
        flat_params = flat[:len(leaves)]
        flat_args = flat[len(leaves):]
        tree = jax.tree_util.tree_unflatten(treedef, list(flat_params))
        args, k = [], 0
        for td in arg_treedefs:
            args.append(jax.tree_util.tree_unflatten(td, list(flat_args[k:k + td.num_leaves])))
            k += td.num_leaves
        return loss_fn(tree, *args)

    try:
        jaxpr = jax.make_jaxpr(flat_loss)(*leaves, *arg_leaves).jaxpr
    except Exception:
        return {}

    # Origin: ("param", name, shifts) / ("arg", name, shifts) where shifts is the
    # frozenset of constant offsets the value may carry relative to the leaf
    # ({0} = value-equal; {0, n} = jnp.take's negative wrap by n).
    origin: Dict[Any, Tuple[str, str, frozenset]] = {}
    for var, nm in zip(jaxpr.invars[:len(leaves)], names):
        origin[var] = ("param", nm, frozenset({0}))
    for var, nm in zip(jaxpr.invars[len(leaves):], arg_names):
        origin[var] = ("arg", nm, frozenset({0}))
    # Per-param: every observed gather's index origin (None = untracked indices).
    gathers: Dict[str, set] = {}
    _walk_index_flow(jaxpr, origin, gathers)
    return {param: leafs.copy().pop()
            for param, leafs in gathers.items()
            if len(leafs) == 1 and None not in leafs}


def _literal_int(x) -> Optional[int]:
    if type(x).__name__ == "Literal":
        try:
            v = x.val
            return int(v) if np.ndim(v) == 0 else None
        except (TypeError, ValueError):
            return None
    return None


def _walk_index_flow(jaxpr, origin, gathers):
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in _TRANSPARENT_PRIMS:
            inner = _sub_jaxpr(eqn)
            if inner is not None:
                inner_invars = list(getattr(inner, "invars", []))
                offset = max(len(inner_invars) - len(eqn.invars), 0)
                inner_origin = {}
                for i, outer in enumerate(eqn.invars):
                    o = origin.get(outer) if _is_var(outer) else None
                    j = i + offset
                    if o is not None and j < len(inner_invars):
                        inner_origin[inner_invars[j]] = o
                _walk_index_flow(inner, inner_origin, gathers)
                for outer_out, inner_out in zip(eqn.outvars,
                                                getattr(inner, "outvars", [])):
                    o = inner_origin.get(inner_out) if _is_var(inner_out) else None
                    if o is not None:
                        origin[outer_out] = o
                continue
        if prim == "gather" and len(eqn.invars) >= 2:
            o_param = origin.get(eqn.invars[0]) if _is_var(eqn.invars[0]) else None
            o_idx = origin.get(eqn.invars[1]) if _is_var(eqn.invars[1]) else None
            if o_param is not None and o_param[0] == "param" and o_param[2] == {0}:
                leaf = None
                if o_idx is not None and o_idx[0] == "arg":
                    dim0 = getattr(getattr(eqn.invars[0], "aval", None), "shape",
                                   (None,))[0]
                    # Accept value-equal indices ({0}) or take's wrap ({0, dim0});
                    # the synchronizer re-applies the wrap for negative indices.
                    if o_idx[2] == {0} or (dim0 and o_idx[2] == {0, dim0}):
                        leaf = o_idx[1]
                gathers.setdefault(o_param[1], set()).add(leaf)
            continue
        if prim in _IDX_EXACT_PRIMS:
            origins = {origin[v] for v in eqn.invars if _is_var(v) and v in origin}
            if len(origins) == 1:
                o = next(iter(origins))
                for out in eqn.outvars:
                    origin[out] = o
        elif prim in ("add", "sub"):
            # Constant shift of a tracked value: record the offset so the wrap
            # pattern (idx and idx+dim0) stays recognizable; anything else is a
            # value change and stops provenance at the gather check.
            var_ops = [v for v in eqn.invars if _is_var(v)]
            lits = [_literal_int(v) for v in eqn.invars if not _is_var(v)]
            if len(var_ops) == 1 and var_ops[0] in origin and len(lits) == 1 \
                    and lits[0] is not None:
                kind, name, shifts = origin[var_ops[0]]
                delta = lits[0] if prim == "add" else -lits[0]
                origin[eqn.outvars[0]] = (kind, name,
                                          frozenset(s + delta for s in shifts))
        elif prim == "select_n":
            # Branches of one tracked value (take's negative wrap): union shifts.
            cases = [v for v in eqn.invars[1:] if _is_var(v)]
            if cases and all(v in origin for v in cases):
                kinds = {origin[v][:2] for v in cases}
                if len(kinds) == 1:
                    kind, name = next(iter(kinds))
                    shifts = frozenset().union(*(origin[v][2] for v in cases))
                    origin[eqn.outvars[0]] = (kind, name, shifts)


def _collect_consumers(jaxpr, consumers):
    for eqn in jaxpr.eqns:
        transparent = eqn.primitive.name in _TRANSPARENT_PRIMS
        inner = _sub_jaxpr(eqn) if transparent else None
        if inner is not None:
            # Map outer invars to inner invars positionally (holds for pjit/call-style
            # primitives) and recurse so a gather inside jnp.take's wrapper is seen.
            inner_invars = list(getattr(inner, "invars", []))
            offset = len(inner_invars) - len(eqn.invars)  # leading consts, if any
            for i, outer in enumerate(eqn.invars):
                if not (_is_var(outer) and outer in consumers):
                    continue
                j = i + max(offset, 0)
                if j < len(inner_invars):
                    tmp = {inner_invars[j]: set()}
                    _collect_consumers(inner, tmp)
                    consumers[outer] |= tmp[inner_invars[j]]
            continue
        for invar in eqn.invars:
            if _is_var(invar) and invar in consumers:
                consumers[invar].add(eqn.primitive.name)
