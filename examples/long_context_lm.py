"""Long-context LM training: flash attention + remat + fused head (+ optional
sequence parallelism).

The composition that sets the single-chip context ceiling (README
§long-context: one v5e chip trains the flagship architecture at seq 8,192 ~97k
tokens/s up to seq 65,536 ~17k, where plain dot-product attention OOMs at
8,192 already):

    PYTHONPATH=. python examples/long_context_lm.py --seq_len 8192
    PYTHONPATH=. python examples/long_context_lm.py --seq_len 65536 --batch_size 1
    # sequence parallelism over a mesh axis (ring attention across shards):
    PYTHONPATH=. python examples/long_context_lm.py --seq_len 4096 --seq_axis 2

- ``--attention auto`` (default) picks the pallas flash kernel where the
  Mosaic backend compiles it and the pure-JAX blockwise path elsewhere (CPU).
- ``--seq_axis k`` switches to the SequenceParallel strategy: activations
  shard over a ``seq`` mesh axis and attention runs as ring attention, each
  shard stepping the flash carry variant (reference has no long-context
  support at all — SURVEY.md §5.7).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import transformer_lm
from autodist_tpu.ops import mosaic_compiles
from autodist_tpu.strategy import AllReduce, SequenceParallel
from autodist_tpu.utils import flops as flops_util


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq_len", type=int, default=8192)
    parser.add_argument("--batch_size", type=int, default=0,
                        help="global batch (default: fills to ~393k tokens)")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--d_model", type=int, default=512)
    parser.add_argument("--n_layers", type=int, default=6)
    parser.add_argument("--vocab", type=int, default=32_000)
    parser.add_argument("--attention", default="auto",
                        choices=["auto", "flash", "blockwise", "dot"])
    parser.add_argument("--seq_axis", type=int, default=0,
                        help=">1 enables sequence parallelism over that many "
                             "mesh shards (ring attention)")
    parser.add_argument("--no_remat", action="store_true")
    args = parser.parse_args(argv)

    on_accel = jax.default_backend() != "cpu"
    if args.attention == "auto":
        # Pallas flash where Mosaic compiles it; elsewhere the pure-JAX
        # blockwise path keeps the O(L) memory profile this example is about.
        attention = "flash" if mosaic_compiles() else "blockwise"
    else:
        attention = args.attention
    if args.seq_axis > 1:
        if args.attention not in ("auto",):
            parser.error(f"--seq_axis {args.seq_axis} shards the sequence and "
                         f"runs ring attention across shards; it cannot honor "
                         f"--attention {args.attention} (drop the flag)")
        attention = "ring"

    # Default batch: keep ~393k tokens in flight (the flagship bench's 384*256*4)
    # but at least one sequence.
    batch_size = args.batch_size or max(1, 393_216 // args.seq_len)
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=8,
        n_layers=args.n_layers, d_ff=4 * args.d_model,
        max_len=args.seq_len, dtype=jnp.bfloat16 if on_accel else jnp.float32,
        tied_output=False, remat=not args.no_remat,
        attention_impl=attention, fused_head=mosaic_compiles())

    model, params = transformer_lm.init_params(cfg)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=args.seq_len)

    if args.seq_axis > 1:
        from autodist_tpu.parallel.sequence import create_sequence_parallel_session
        ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=args.seq_axis))
        runner = create_sequence_parallel_session(ad, model, params,
                                                  optax.adam(1e-3))
        state = runner.init(params)

        def step_fn(b):
            nonlocal state
            state, loss = runner.run(state, b)
            return loss
    else:
        ad = AutoDist(strategy_builder=AllReduce())
        loss_fn = transformer_lm.make_loss_fn(model)
        step_fn = ad.function(loss_fn, params, optax.adam(1e-3),
                              example_batch=batch)
        runner = step_fn.runner
    batch = runner.shard_batch(batch)

    loss = step_fn(batch)
    _ = float(loss)  # compile fence
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step_fn(batch)
    _ = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * args.seq_len
    rate = tokens_per_step * args.steps / dt
    print(f"long-context seq={args.seq_len} bs={batch_size} "
          f"attention={attention} remat={cfg.remat} "
          f"(mesh={dict(runner.mesh.shape)}): final loss {float(loss):.4f}, "
          f"{rate:,.0f} tokens/sec")
    fpt = flops_util.transformer_flops_per_token(
        cfg.d_model, cfg.n_layers, cfg.d_ff, cfg.vocab_size, args.seq_len)
    flops_util.report_mfu(fpt * tokens_per_step / len(jax.devices()),
                          rate / tokens_per_step)
    return rate


if __name__ == "__main__":
    main()
