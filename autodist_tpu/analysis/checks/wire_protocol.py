"""GL006 — wire-protocol exhaustiveness and frame-version ordering.

A byte-level mismatch between wire endpoints costs a whole run: an opcode the
client sends but the server never dispatches turns into a per-step (or
per-request) "unknown op" error loop; a codec tag with an encode arm but no
decode arm is a guaranteed ``WireError`` at the first message carrying it;
and parsing a payload length before validating the frame-version byte
misreads an incompatible future framing as an absurd length (exactly what
the PR 2 framing redesign guarded against).

Two transports speak this wire today — the PS training plane
(``parallel/ps_transport.py``) and the serving plane
(``serving/transport.py``) — and the check is deliberately SHAPE-based, not
path-based: any module pairing ``.call("op", ...)`` client sends with a
``_dispatch`` arm table gets the same exhaustiveness guarantee, so the next
transport is covered the day it is written. A module may host several server
classes (each with its own ``_dispatch``); an op is satisfied when ANY of
them handles it.
"""

import ast
from typing import List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register


def _str_compares(fn, var: str) -> Set[str]:
    """String constants ``var`` is compared against (==, or ``in (tuple)``)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id == var):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq) and isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, str):
                out.add(comp.value)
            elif isinstance(op, ast.In) \
                    and isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                out.update(e.value for e in comp.elts
                           if isinstance(e, ast.Constant)
                           and isinstance(e.value, str))
    return out


def _sent_ops(tree: ast.Module) -> List[Tuple[str, ast.Call]]:
    """(op, call) pairs for client sends: ``.call("op", ...)`` and
    ``.call_raw(("op", ...), ...)``."""
    out: List[Tuple[str, ast.Call]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        last = callgraph.last_attr(node.func)
        if last == "call" and isinstance(node.func, ast.Attribute) \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node))
        elif last == "call_raw" and node.args \
                and isinstance(node.args[0], ast.Tuple) \
                and node.args[0].elts \
                and isinstance(node.args[0].elts[0], ast.Constant) \
                and isinstance(node.args[0].elts[0].value, str):
            out.append((node.args[0].elts[0].value, node))
    return out


def _bytes_tags_appended(fn) -> Set[bytes]:
    """Single-byte bytes constants appended ``out += b"X"`` in the encoder."""
    out: Set[bytes] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, bytes) \
                and len(node.value.value) == 1:
            out.add(node.value.value)
    return out


def _bytes_tags_compared(fn, var: str) -> Set[bytes]:
    out: Set[bytes] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id == var):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq) and isinstance(comp, ast.Constant) \
                    and isinstance(comp.value, bytes) \
                    and len(comp.value) == 1:
                out.add(comp.value)
    return out


@register("GL006", "wire opcode/tag without a matching peer arm; "
                   "frame version unchecked")
def check_wire_protocol(module: Module, ctx: Context) -> List[Finding]:
    """GL006 — wire-opcode exhaustiveness.

    Three structural invariants of the zero-copy wire (``parallel/wire.py``,
    spoken by ``parallel/ps_transport.py`` AND the serving transport
    ``serving/transport.py``), checked wherever the same shapes appear:

    - Every opcode a client sends (``.call("op", ...)`` /
      ``.call_raw(("op", ...))``) must have a dispatch arm (``op == "..."``)
      in one of the module's ``_dispatch`` functions (module-level or
      method; arms union across server classes). A missing arm is a
      per-step error loop in training and a 100%-error-rate op in serving —
      e.g. adding a ``read_min`` client without the server arm would break
      every overlapped worker against the new chief, and a serving client
      op without an ``InferenceServer._dispatch`` arm rejects every request
      carrying it.
    - In a codec module (functions named ``_enc``/``_dec``): every one-byte
      tag the encoder emits (``out += b"X"``) must have a decode arm
      (``tag == b"X"``) and vice versa — an asymmetric tag is a guaranteed
      WireError on the first message that carries it.
    - In a module defining ``_FRAME_VERSION``: any function unpacking the
      frame header struct (a name containing ``HDR``) must reference
      ``_FRAME_VERSION`` — i.e. version validation and length parsing stay
      in one place (``_frame_len``), so an incompatible future framing is
      rejected instead of misparsed as a length.
    """
    if module.tree is None:
        return []
    findings: List[Finding] = []
    index = callgraph.ModuleIndex(module.tree)

    # -- opcode exhaustiveness (gated on a _dispatch function existing) -----
    # Union the arms of EVERY _dispatch in the module (module-level function
    # plus any number of methods): the serving transport hosts its dispatcher
    # as a server-class method, and a module with several server classes
    # must not check one client's ops against another class's arm table.
    dispatchers = []
    if "_dispatch" in index.module_funcs:
        dispatchers.append(index.module_funcs["_dispatch"])
    dispatchers.extend(fn for (cls, name), fn in index.methods.items()
                       if name == "_dispatch")
    handled: Set[str] = set()
    for dispatch in dispatchers:
        handled |= _str_compares(dispatch, "op")
    if handled:
        for op, call in _sent_ops(module.tree):
            if op not in handled:
                findings.append(Finding(
                    "GL006", module.relpath, call.lineno, call.col_offset,
                    f"opcode {op!r} is sent but `_dispatch` has no arm "
                    f"for it; every request would error as unknown-op",
                    scope=module.scope_at(call)))

    # -- codec tag symmetry (gated on _enc/_dec both existing) --------------
    enc = index.module_funcs.get("_enc")
    dec = index.module_funcs.get("_dec")
    if enc is not None and dec is not None:
        enc_tags = _bytes_tags_appended(enc)
        dec_tags = _bytes_tags_compared(dec, "tag")
        if enc_tags and dec_tags:
            for tag in sorted(enc_tags - dec_tags):
                findings.append(Finding(
                    "GL006", module.relpath, enc.lineno, enc.col_offset,
                    f"wire tag {tag!r} is encoded by `_enc` but `_dec` has "
                    f"no decode arm; round-trips of values carrying it "
                    f"raise WireError", scope=module.scope_at(enc)))
            for tag in sorted(dec_tags - enc_tags):
                findings.append(Finding(
                    "GL006", module.relpath, dec.lineno, dec.col_offset,
                    f"wire tag {tag!r} has a decode arm in `_dec` but is "
                    f"never encoded; dead arm or a missing encoder branch",
                    scope=module.scope_at(dec)))

    # -- frame-version-before-length (gated on _FRAME_VERSION existing) -----
    has_version = any(
        isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "_FRAME_VERSION"
            for t in n.targets)
        for n in module.tree.body)
    if has_version:
        all_fns = list(index.module_funcs.values()) \
            + list(index.methods.values())
        for fn in all_fns:
            unpacks_hdr = any(
                isinstance(c, ast.Call)
                and callgraph.last_attr(c.func) == "unpack"
                and "HDR" in (callgraph.dotted_name(c.func) or "").upper()
                for c in callgraph.calls_under(fn))
            if not unpacks_hdr:
                continue
            refs_version = any(
                isinstance(n, ast.Name) and n.id == "_FRAME_VERSION"
                for n in ast.walk(fn))
            if not refs_version:
                findings.append(Finding(
                    "GL006", module.relpath, fn.lineno, fn.col_offset,
                    f"`{fn.name}` unpacks the frame header without checking "
                    f"_FRAME_VERSION; version validation must precede "
                    f"payload-length parsing (route through _frame_len)",
                    scope=module.scope_at(fn)))
    return findings
