"""HBM memory plane: census, budget, pressure, pre-flight, forensics.

Covers the memory plane end to end (docs/usage/observability.md "Memory
plane") without a single compile probe or training step:

- the tag registry: static and weakref tree claims, re-tag replacement,
  dead-claim pruning, and the ``other`` residual's never-negative clamp;
- budget resolution order (env override vs the warned default on a
  backend with no allocator limit) and the pressure fallback
  (live/budget) that lets a tiny ``AUTODIST_MEM_BUDGET`` inject a squeeze
  on CPU — the degrade paths the plane must survive;
- the shipped ``mem_pressure`` alert rule (pinned verbatim, sustained-not-
  spike semantics) and the squeeze-to-firing path through a real
  ``MetricsHistory`` sample;
- OOM forensics: ``is_oom_error`` recognition, ``record_oom`` writing a
  flight-recorder snapshot whose manifest ``memory`` section names the
  dominant owner;
- the autotuner memory pre-flight: analytic resident model (async / ZeRO /
  accumulation / partition discount), never-fit candidates refused with
  ``pruned: oom`` and ZERO compile probes spent (poisoned-AutoDist pin),
  and ``costmodel.predict``'s ``peak_hbm_bytes``;
- the stable status/snapshot shells and the adtop memory lines.

Pure in-process host tests — no subprocess spawns (GL008-clean), named
test_zmemplane to sort at the tier-1 window's tail (after
test_wire_compress); the whole file budgets well under 15s.
"""

import gc
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import const, telemetry  # noqa: E402
from autodist_tpu.model_spec import ModelSpec  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy.autotune import (Candidate,  # noqa: E402
                                            TunedPlan,
                                            _predicted_resident_bytes,
                                            _probe_base_costs, autotune,
                                            enumerate_candidates)
from autodist_tpu.telemetry import alerts  # noqa: E402
from autodist_tpu.telemetry import costmodel  # noqa: E402
from autodist_tpu.telemetry import history as _history  # noqa: E402
from autodist_tpu.telemetry import memplane  # noqa: E402
from autodist_tpu.telemetry import metrics as _metrics  # noqa: E402
from autodist_tpu.telemetry import recorder  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    """Leave process-global telemetry/memplane/recorder/alerts as found."""
    telemetry.disable()
    telemetry.clear()
    memplane.reset()
    recorder.set_recorder(None)
    alerts.set_engine(None)
    yield
    telemetry.disable()
    telemetry.clear()
    memplane.reset()
    recorder.set_recorder(None)
    alerts.set_engine(None)


# ------------------------------------------------------------------ fixtures

def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)


def _params():
    return {"w": np.random.RandomState(0).randn(8, 4).astype(np.float32)}


def _batch(rows=16):
    rng = np.random.RandomState(1)
    return {"x": rng.randn(rows, 8).astype(np.float32),
            "y": rng.randn(rows, 4).astype(np.float32)}


# --------------------------------------------------------------------- flags

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_MEM_BUDGET", "AUTODIST_MEM_PRESSURE"):
        assert flag in const.KNOWN_FLAGS and const.KNOWN_FLAGS[flag]
        assert hasattr(const.ENV, flag)
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", "123456")
    assert const.ENV.AUTODIST_MEM_BUDGET.val == 123456
    monkeypatch.setenv("AUTODIST_MEM_PRESSURE", "0.5")
    assert const.ENV.AUTODIST_MEM_PRESSURE.val == 0.5
    assert memplane.pressure_threshold() == 0.5
    monkeypatch.delenv("AUTODIST_MEM_PRESSURE")
    assert memplane.pressure_threshold() == 0.92


# ------------------------------------------------------------- tag registry

def test_tag_census_attribute_and_residual_clamp():
    memplane.tag("kv_pages", 1000)                     # static bytes claim
    arr = jnp.ones((128,), jnp.float32) * 2.0          # 512 device bytes
    tree = {"w": arr}
    memplane.tag("params", tree)                       # weakref tree claim
    counts = memplane.census()
    assert counts["kv_pages"] == 1000
    assert counts["params"] == 512
    owned = memplane.attribute(2000)
    assert set(owned) == set(memplane.OWNERS) | {"other"}
    assert owned["params"] == 512 and owned["kv_pages"] == 1000
    assert owned["opt_state"] == 0                     # unclaimed -> 0, stable
    assert owned["other"] == 2000 - 1512
    # The residual is a leak detector: claims overshooting the live gauge
    # must clamp to 0, never report a negative leak.
    assert memplane.attribute(100)["other"] == 0
    # Re-tag replaces; untag drops (idempotent).
    memplane.tag("kv_pages", 777)
    assert memplane.census()["kv_pages"] == 777
    memplane.untag("kv_pages")
    memplane.untag("kv_pages")
    assert "kv_pages" not in memplane.census()
    del tree, arr


def test_weakref_claim_dies_with_the_tree():
    arr = jnp.arange(256, dtype=jnp.float32) + 1.0
    memplane.tag("prefetch", {"batch": arr}, key="feed.0")
    assert memplane.census()["prefetch"] == 1024
    del arr
    gc.collect()
    assert "prefetch" not in memplane.census()
    # Keyed claims scope concurrent claimants of one owner.
    memplane.tag("kv_pages", 100, key="pool.a")
    memplane.tag("kv_pages", 200, key="pool.b")
    assert memplane.census()["kv_pages"] == 300


# ------------------------------------------------------- budget and pressure

def test_device_budget_env_and_default_sources(monkeypatch):
    # CPU reports no allocator limit, so the env override wins when set...
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", "123456789")
    budget, source = memplane.device_budget()
    assert (budget, source) == (123456789, "env")
    snap = _metrics.snapshot()
    assert snap["mem.budget_bytes"] == 123456789
    assert snap["mem.budget_source"] == 1.0
    # ...and the warned 8 GiB default backstops when nothing answers.
    monkeypatch.delenv("AUTODIST_MEM_BUDGET")
    budget, source = memplane.device_budget()
    assert (budget, source) == (memplane.DEFAULT_BUDGET_BYTES, "default")
    assert _metrics.snapshot()["mem.budget_source"] == 0.0


def test_pressure_fallback_drives_kv_holdback(monkeypatch):
    # No allocator stats on CPU -> pressure degrades to live/budget, so a
    # tiny AUTODIST_MEM_BUDGET injects a squeeze the whole plane reacts to.
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", "1")
    keep = jnp.ones((64,), jnp.float32) + 0.0   # some live bytes to measure
    assert memplane.current_pressure(max_age_s=0.0) > 0.92
    assert memplane.kv_admission_holdback(100) == 25   # 25% of the pool
    assert memplane.kv_admission_holdback(1) == 1      # max(1, ...) floor
    assert memplane.kv_admission_holdback(0) == 0      # empty pool: inert
    # Below the threshold the holdback vanishes — admission is unchanged.
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", str(1 << 50))
    assert memplane.current_pressure(max_age_s=0.0) < 0.92
    assert memplane.kv_admission_holdback(100) == 0
    del keep


# ------------------------------------------------------------ degrade paths

def test_sample_device_memory_degrades_clean_on_cpu():
    """CPU reports no allocator stats and opt_state=None skips the
    opt-state gauge — the attributed sample must still book the census
    and pressure, and never raise."""
    # Earlier suites may already have booked train.opt_state_bytes in the
    # process-global registry — pin "this call left it untouched", not
    # global absence.
    before = _metrics.snapshot().get("train.opt_state_bytes")
    arr = jnp.ones((64,), jnp.float32) * 3.0
    memplane.tag("params", {"w": arr})
    wrote = telemetry.sample_device_memory()           # opt_state=None
    assert wrote > 0
    snap = _metrics.snapshot()
    assert snap.get("train.opt_state_bytes") == before
    assert snap["device.live_bytes"] >= 256
    for owner in memplane.OWNERS + ("other",):
        assert f"mem.owned.{owner}" in snap
    assert snap["mem.owned.params"] == 256
    assert snap["mem.owned.other"] >= 0
    assert "mem.pressure" in snap
    del arr


def test_memory_snapshot_shell_is_stable_when_unarmed():
    assert memplane.memory_snapshot() == {
        "owned": {}, "live_bytes": 0, "pressure": 0.0, "budget_bytes": 0,
        "budget_source": "", "devices": {}}


def test_memory_snapshot_and_section_when_armed():
    arr = jnp.ones((512,), jnp.float32) + 0.0
    memplane.tag("params", {"w": arr})                 # claims arm the plane
    snap = memplane.memory_snapshot()
    assert snap["live_bytes"] >= 2048
    assert snap["owned"]["params"] == 2048
    assert snap["budget_source"] in ("default", "env", "measured")
    section = memplane.memory_section()
    for key in ("programs", "history", "predicted_peak_bytes",
                "live_peak_bytes", "peak_delta_bytes"):
        assert key in section
    # The autopsy's opening line: predicted resident covers the claims.
    assert section["predicted_peak_bytes"] >= 2048
    json.dumps(section)                                # wire/manifest-encodable
    del arr


def test_snapshot_ring_states_feed_the_census():
    from autodist_tpu.parallel.recovery import SnapshotRing
    ring = SnapshotRing(keep=2)
    a = jnp.ones((32,), jnp.float32) * 1.0
    b = jnp.ones((32,), jnp.float32) * 2.0
    ring.push(1, {"w": a})
    ring.push(2, {"w": b})
    states = ring.states()
    assert len(states) == 2                            # oldest first, public
    memplane.tag("snapshots", states)
    assert memplane.census()["snapshots"] == 256       # both retained states


# -------------------------------------------------------------- alert rule

def test_mem_pressure_rule_shipped_verbatim():
    entry = next(r for r in alerts.DEFAULT_RULES if r["name"] == "mem_pressure")
    assert entry == {"name": "mem_pressure", "kind": "threshold",
                     "metric": "mem.pressure", "op": ">", "value": 0.92,
                     "for_s": 30.0}


class _FakeHistory:
    """Duck-typed history ring with synthetic timestamps — lets the 30s
    sustain window be tested without 30s of wall clock."""

    def __init__(self, rows):
        self._rows = rows

    def latest(self):
        return self._rows[-1] if self._rows else None

    def samples(self):
        return list(self._rows)

    def window(self, seconds, now=None):
        cut = self._rows[-1]["t_mono_s"] - seconds
        return [r for r in self._rows if r["t_mono_s"] >= cut]


def test_mem_pressure_rule_fires_sustained_not_spike():
    rule = alerts.AlertRule.from_dict(
        next(r for r in alerts.DEFAULT_RULES if r["name"] == "mem_pressure"))

    def row(t, value):
        return {"t_mono_s": t, "metrics": {"mem.pressure": value}}

    # One fresh spike proves nothing about duration: no firing.
    assert rule.evaluate(_FakeHistory([row(1000.0, 0.99)])) is None
    # 40s of sustained pressure: fires with value and bound.
    sustained = _FakeHistory([row(1000.0 + 5 * i, 0.97) for i in range(9)])
    detail = rule.evaluate(sustained)
    assert detail == {"value": 0.97, "bound": 0.92}
    # A recovery inside the window resets the incident.
    dipped = _FakeHistory([row(1000.0 + 5 * i, 0.97) for i in range(8)]
                          + [row(1038.0, 0.5), row(1040.0, 0.97)])
    assert rule.evaluate(dipped) is None


def test_injected_squeeze_fires_through_history_sample(monkeypatch):
    """The e2e squeeze pin: tiny budget -> mem.pressure books past the
    threshold on the attributed sample -> the rule fires on the very next
    history tick -> forensics name the dominant owner."""
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", "1")
    arr = jnp.ones((1024,), jnp.float32) * 2.0
    memplane.tag("params", {"w": arr})
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="mem_pressure", kind="threshold", metric="mem.pressure",
        op=">", value=0.92)], action="warn")   # for_s=0: fire on first tick
    alerts.set_engine(eng)
    telemetry.sample_device_memory()                   # books mem.pressure
    h = _history.MetricsHistory(out_dir="", min_interval_s=0.0, engine=eng)
    h.sample()
    assert [a["rule"] for a in eng.active()] == ["mem_pressure"]
    section = memplane.memory_section()
    dominant = max(memplane.OWNERS, key=lambda o: section["owned"][o])
    assert dominant == "params"
    del arr


# ------------------------------------------------------------ OOM forensics

def test_is_oom_error_recognition():
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    assert memplane.is_oom_error(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 123456 bytes"))
    assert memplane.is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: while allocating buffer"))
    assert not memplane.is_oom_error(ValueError("shape mismatch (8,4)"))
    assert not memplane.is_oom_error(XlaRuntimeError("INVALID_ARGUMENT"))


def test_record_oom_writes_memory_autopsy(tmp_path):
    recorder.set_recorder(recorder.FlightRecorder(
        str(tmp_path / "fr"), keep=2, min_interval_s=0.0))
    arr = jnp.ones((1024,), jnp.float32) + 0.0
    memplane.tag("params", {"w": arr})
    memplane.tag("kv_pages", 64)
    XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})
    memplane.record_oom("train_step", XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 4096 bytes"))
    assert _metrics.snapshot()["mem.oom"] == 1
    snaps = recorder.get_recorder().snapshots()
    assert len(snaps) == 1 and "oom.train_step" in snaps[0]
    manifest = json.load(open(os.path.join(snaps[0], "manifest.json")))
    owned = manifest["memory"]["owned"]
    assert owned["params"] == 4096 and owned["kv_pages"] == 64
    assert max(memplane.OWNERS, key=lambda o: owned[o]) == "params"
    del arr


# ------------------------------------------------------- autotune pre-flight

def test_predicted_resident_bytes_analytic_model():
    sync = Candidate({"name": "AllReduce"})
    assert _predicted_resident_bytes(sync, 100, 50, 8) == 150
    zero = Candidate({"name": "AllReduce"}, zero=1)
    assert _predicted_resident_bytes(zero, 100, 50, 8) == 100 + 50 // 8
    accum = Candidate({"name": "AllReduce"}, accumulation_steps=2)
    assert _predicted_resident_bytes(accum, 100, 50, 8) == 250
    async_c = Candidate({"name": "PS", "kwargs": {"sync": False}},
                        asynchronous=True)
    assert _predicted_resident_bytes(async_c, 100, 50, 8) == 200
    # No exact opt-state footprint: the Adam-shaped 2x-params fallback.
    assert _predicted_resident_bytes(sync, 100, None, 8) == 300


def test_preflight_refuses_never_fit_with_zero_compile_probes(monkeypatch):
    """The e2e oom pin: with a budget below even the model's resident
    params, EVERY candidate is refused before stage 1 and not one compile
    probe is spent (a poisoned AutoDist would raise if one were)."""
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", "64")    # dense params are 128B
    calls = []

    class _PoisonAutoDist:
        def __init__(self, *a, **kw):
            calls.append(a)
            raise AssertionError(
                "compile probe spent on a pre-flight-refused candidate")

    monkeypatch.setattr("autodist_tpu.autodist.AutoDist", _PoisonAutoDist)
    spec = ModelSpec(_params())
    cands = enumerate_candidates(spec, ResourceSpec(None), optax.sgd(0.1),
                                 unrolls=(1, 2), accums=(1,))
    assert cands
    for c in cands:
        assert c.resident_bytes is not None and c.resident_bytes > 64
        assert c.pruned and c.pruned.startswith("oom:")
    base_costs = _probe_base_costs(cands, _loss, _params(), optax.sgd(0.1),
                                   _batch(), ResourceSpec(None), None, False)
    assert base_costs == {} and calls == []
    # The refusal reason renders in the explain table...
    table = TunedPlan(builder_spec={"name": "AllReduce"}, candidates=cands,
                      enumerated=len(cands)).explain()
    assert "pruned: oom: predicted resident" in table
    # ...and a full search against the same budget refuses up front,
    # naming the oom reasons — still zero probes (the poison is live).
    with pytest.raises(RuntimeError, match="oom: predicted resident"):
        autotune(_loss, _params(), optax.sgd(0.1), _batch(),
                 plan_cache="", unrolls=(1,), top_k=1)
    assert calls == []


def test_preflight_partition_discount_spares_sharded_plans(monkeypatch):
    """A 64 MiB param over 8 devices: the dense plans' resident state
    busts a 16 MiB budget, but the partitioned builders keep that param
    sharded 1/n_dev — refusing them on the DENSE footprint would prune
    exactly the plans that fit."""
    monkeypatch.setenv("AUTODIST_MEM_BUDGET", str(16 << 20))
    spec = ModelSpec({"big": np.zeros((4096, 4096), np.float32)})
    cands = enumerate_candidates(spec, ResourceSpec(None), optax.sgd(0.1),
                                 unrolls=(1,), accums=(1,))
    by_name = {}
    for c in cands:
        by_name.setdefault(c.builder_spec["name"], []).append(c)
    assert all(c.pruned and c.pruned.startswith("oom:")
               for c in by_name["AllReduce"])
    assert any(not c.pruned for c in by_name["PartitionedAR"])


def test_costmodel_predict_carries_peak_hbm():
    calib = costmodel.Calibration(flops_per_s=1e12, bytes_per_s=1e11,
                                  host_s_per_dispatch=1e-3)
    rec = {"flops": 1e9, "bytes_accessed": 1e6, "steps": 1, "dispatches": 1,
           "temp_bytes": 4096}
    pred = costmodel.predict(rec, calib, resident_bytes=1000.0)
    assert pred["peak_hbm_bytes"] == 1000 + 4096
    # No temp ledger: argument + output bytes stand in for the transient.
    rec2 = {"flops": 1e9, "argument_bytes": 10, "output_bytes": 20}
    assert costmodel.predict(rec2, calib)["peak_hbm_bytes"] == 30
    # Neither resident nor any memory ledger: honestly None, not 0.
    assert costmodel.predict({"flops": 1e9}, calib)["peak_hbm_bytes"] is None


# ------------------------------------------------------------------ console

def test_adtop_memory_lines_render():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "adtop", os.path.join(os.path.dirname(__file__), os.pardir,
                              "tools", "adtop.py"))
    adtop = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(adtop)
    status = {"memory": {
        "owned": {"params": 4096, "opt_state": 8192, "kv_pages": 0,
                  "prefetch": 0, "snapshots": 0, "other": 100},
        "live_bytes": 12388, "pressure": 0.9412,
        "budget_bytes": 8 << 30, "budget_source": "default", "devices": {}}}
    lines = adtop._memory_lines(status)
    head = lines[0]
    assert "mem" in head and "pressure 0.94" in head
    assert any("opt_state" in ln for ln in lines[1:])
    # The unarmed shell renders nothing — no dead rows on healthy consoles.
    assert adtop._memory_lines({"memory": {
        "owned": {}, "live_bytes": 0, "pressure": 0.0, "budget_bytes": 0,
        "budget_source": "", "devices": {}}}) == []
    assert adtop._memory_lines({}) == []
