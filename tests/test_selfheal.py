"""Self-healing runtime: the detect→act loop, driven by REAL injected faults.

Covers the recovery plane end to end (docs/usage/resilience.md):

- deterministic fault points (``testing/faults.py``): spec parsing, exact
  step/worker keying, count-bounded consumption under concurrency;
- wire-level retry: injected connect refusals and mid-call resets retry
  IDEMPOTENT opcodes with jittered backoff, surface non-idempotent ones;
- auto-eviction: a sustained stall past ``AUTODIST_EVICT_AFTER_S`` retires
  the worker from the staleness gate (one deterministic watchdog tick), the
  gate unwedges, a parked gate RPC fails typed (``WorkerEvicted``);
- rejoin with catch-up: an evicted remote worker auto-rejoins seeded at the
  slowest live count and pulls the chief's LIVE params over ``read_min``; a
  crashed worker's replacement continues BIT-IDENTICALLY vs an unfailed run;
- recover action: injected NaN under ``AUTODIST_HEALTH_ACTION=recover``
  rolls back to the last-known-good snapshot and the run FINISHES with
  finite (and bit-identical, callable-source) params; ``AUTODIST_RECOVER_
  MAX`` exhaustion escalates to the existing :class:`HealthHalt`;
- the coordinator's ``AUTODIST_WORKER_FAILURE=respawn`` policy (budgeted,
  backed-off relaunch instead of ``os._exit(1)``);
- the ``status`` opcode's ``recovery`` section + adtop/adfleet rendering;
- the new flag registrations.

Pure in-process host tests — no subprocess spawns; sorts after the tier-1
window edge and stays cheap (tiny scalar/linear models, bounded waits only).
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const, telemetry, train  # noqa: E402
from autodist_tpu.parallel import recovery  # noqa: E402
from autodist_tpu.parallel.staleness import (ParameterService,  # noqa: E402
                                             StalenessController,
                                             WorkerEvicted)
from autodist_tpu.runner import TrainState  # noqa: E402
from autodist_tpu.strategy import PS, AllReduce  # noqa: E402
from autodist_tpu.telemetry import health  # noqa: E402
from autodist_tpu.testing import faults  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test starts and ends with the fault harness disarmed — an
    armed plan leaking across tests would fire in an unrelated step loop."""
    faults.clear()
    yield
    faults.clear()


# ------------------------------------------------------------------ fixtures

BATCH = 16


def _ps_data(seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH).astype(np.float32)
    return {"x": x, "y": (2.0 * x - 1.0).astype(np.float32)}


def _ps_loss(p, b):
    return jnp.mean((b["y"] - (b["x"] * p["w"] + p["b"])) ** 2)


def _ps_params():
    return {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}


def _ps_session(num_workers=2, staleness=2):
    # staleness=0 needs sync=False to select the async (fully unbounded)
    # regime; staleness>0 is bounded-stale with the default sync flag.
    ad = AutoDist(strategy_builder=PS(sync=staleness > 0,
                                      staleness=staleness))
    runner = ad.create_distributed_session(
        _ps_loss, _ps_params(), optax.sgd(0.05), example_batch=_ps_data(),
        num_workers=num_workers)
    runner.init(_ps_params())
    return runner


class _StubPSRunner:
    """The minimal surface PSServer._dispatch drives (the test_health_plane
    pattern): a real gate + numpy-only ParameterService, no compilation."""

    def __init__(self, num_workers=2, staleness=1):
        state = TrainState(step=np.zeros((), np.int32),
                           params={"w": np.ones((8,), np.float32)},
                           opt_state=(), ef_state=())
        self.service = ParameterService(state, lambda s, grads: s)
        self.controller = StalenessController(num_workers,
                                              staleness=staleness)

    def add_worker(self, worker_id=None, with_generation=False):
        wid, gen = self.controller.register_with_generation(worker_id)
        handle = type("H", (), {"worker_id": wid})()
        return (handle, gen) if with_generation else handle


def _loopback_stub(num_workers=2, staleness=1):
    from autodist_tpu.parallel.ps_transport import PSServer
    server = PSServer(_StubPSRunner(num_workers, staleness),
                      host="127.0.0.1", watchdog=False)
    return server, "%s:%d" % server.address


def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)


def _params():
    return {"w": np.random.RandomState(0).randn(4, 1).astype(np.float32)}


def _batch(i):
    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(32, 4).astype(np.float32),
            "y": rng.randn(32, 1).astype(np.float32)}


@pytest.fixture(scope="module")
def ar_runner():
    """One compiled AllReduce session shared by the recover-action tests
    (train() re-inits per call; the jit cache is what's being shared)."""
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(
        _loss, _params(), optax.adam(1e-2), example_batch=_batch(0),
        health=True)


# ------------------------------------------------------------- fault harness

def test_fault_spec_parse_roundtrip():
    pts = faults.parse("worker_crash@step=3,worker=1;nan_grads@step=5;"
                       "wire_refuse@count=2;worker_hang@for_s=0.25,worker=0")
    assert [p.kind for p in pts] == ["worker_crash", "nan_grads",
                                    "wire_refuse", "worker_hang"]
    assert pts[0].step == 3 and pts[0].worker == 1 and pts[0].count == 1
    assert pts[2].count == 2
    assert pts[3].for_s == 0.25
    with pytest.raises(ValueError, match="unknown fault kind"):
        faults.parse("explode@step=1")
    with pytest.raises(ValueError, match="unknown key"):
        faults.parse("nan_grads@steps=1")


def test_fault_should_fire_is_deterministic_and_consumed():
    faults.install("worker_crash@step=3,worker=1;wire_refuse@count=2")
    assert faults.armed()
    # Wrong step / wrong worker never fire.
    assert not faults.should_fire("worker_crash", step=2, worker=1)
    assert not faults.should_fire("worker_crash", step=3, worker=0)
    assert faults.should_fire("worker_crash", step=3, worker=1)
    # Consumed: the exact same key cannot fire twice past its count.
    assert not faults.should_fire("worker_crash", step=3, worker=1)
    # Count-bounded under concurrency: 8 threads race for 2 firings.
    hits = []
    def probe():
        if faults.should_fire("wire_refuse"):
            hits.append(1)
    threads = [threading.Thread(target=probe) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert len(hits) == 2
    faults.clear()
    assert not faults.armed()


def test_fault_hang_returns_bounded_duration_and_consumes():
    faults.install("worker_hang@step=2,worker=0,for_s=0.25;"
                   "worker_hang@worker=1,for_s=0.1,count=2")
    assert faults.hang_s(step=1, worker=0) == 0.0    # wrong step: no hang
    assert faults.hang_s(step=2, worker=0) == 0.25
    assert faults.hang_s(step=2, worker=0) == 0.0    # consumed
    assert faults.hang_s(step=9, worker=1) == 0.1    # step-agnostic point
    assert faults.hang_s(step=3, worker=1) == 0.1
    assert faults.hang_s(step=4, worker=1) == 0.0    # count=2 spent


def test_fault_corrupt_batch_nanifies_floats_only():
    b = {"x": np.ones((4, 2), np.float32), "ids": np.arange(4),
         "flag": np.array([True, False])}
    c = faults.corrupt_batch(b)
    assert np.isnan(c["x"]).all()
    assert np.array_equal(c["ids"], b["ids"])
    assert np.array_equal(c["flag"], b["flag"])


# ---------------------------------------------------------------- wire retry

def test_wire_refuse_connect_retries_then_connects():
    from autodist_tpu.parallel.ps_transport import _PSClient
    server, addr = _loopback_stub()
    try:
        faults.install("wire_refuse@count=2")
        client = _PSClient(addr, connect_timeout=10.0)
        assert faults.points()[0].fired == 2   # both refusals consumed
        assert client.call("version")[0] == 0
        client.close()
    finally:
        server.close()


def test_wire_reset_retries_idempotent_surfaces_nonidempotent():
    from autodist_tpu.parallel.ps_transport import (IDEMPOTENT_OPS,
                                                    _PSClient, _retry_safe)
    # The idempotency table itself is part of the contract.
    assert "read" in IDEMPOTENT_OPS and "register" in IDEMPOTENT_OPS
    assert "apply" not in IDEMPOTENT_OPS
    assert "finish_step" not in IDEMPOTENT_OPS
    # register is replay-safe ONLY with an explicit id: register(None)
    # ALLOCATES a fresh slot per request, and a replay would leave a
    # phantom live slot pinning min(steps).
    assert _retry_safe(("register", 3))
    assert not _retry_safe(("register", None))
    assert not _retry_safe(("register",))
    assert not _retry_safe(("apply", {}))
    server, addr = _loopback_stub()
    try:
        client = _PSClient(addr, connect_timeout=10.0)
        faults.install("wire_reset@op=read")
        params, ef, version = client.call("read")   # transparent retry
        assert params is not None and version == 0
        assert faults.points()[0].fired == 1
        faults.install("wire_reset@op=apply")
        with pytest.raises(ConnectionResetError):
            client.call("apply", {"w": np.zeros((8,), np.float32)})
        client.close()
    finally:
        server.close()


def test_backoff_is_bounded_and_grows():
    delays = [recovery.backoff_s(a, 0.2, cap_s=5.0) for a in range(10)]
    assert all(0.0 <= d <= 5.0 for d in delays)
    # The exponential envelope: attempt 5's ceiling is the cap.
    assert recovery.backoff_s(0, 0.2, cap_s=5.0) <= 0.2
    assert recovery.backoff_s(50, 0.2, cap_s=5.0) <= 5.0
    assert recovery.backoff_s(0, 0.0) == 0.0


# ------------------------------------------------------------- auto-eviction

def test_watchdog_evicts_sustained_stall_and_gate_unwedges():
    from autodist_tpu.parallel.ps_transport import _StragglerWatchdog
    server, _ = _loopback_stub(num_workers=2, staleness=1)
    stub = server._runner
    evicted0 = telemetry.counter("recover.evicted").value
    try:
        # Worker 1 never steps: worker 0 runs to the bound then parks.
        stub.controller.start_step(0, timeout=1)
        stub.controller.finish_step(0)
        with pytest.raises(Exception):   # StalenessTimeout: parked at bound
            stub.controller.start_step(0, timeout=0.2)
        # Deterministic watchdog tick with worker 1 long silent.
        server._stats_for(0)
        server._stats_for(1)
        with server._worker_stats_lock:
            server._worker_stats[1].last_seen = time.monotonic() - 999.0
        wd = _StragglerWatchdog(server, interval=60.0, evict_after=30.0)
        try:
            wd._sample()
        finally:
            wd.close()
        assert 1 in stub.controller._retired
        assert telemetry.counter("recover.evicted").value == evicted0 + 1
        assert any(e["name"] == "recover.evicted"
                   for e in telemetry.events())
        # The gate unwedged: worker 0 steps freely past the old bound.
        for _ in range(3):
            stub.controller.start_step(0, timeout=1)
            stub.controller.finish_step(0)
        # status ships the recovery section with the eviction recorded.
        status = server.status_snapshot()
        assert status["recovery"]["counts"]["evicted"] >= 1
        assert any(r["worker"] == 1 and r["kind"] == "stall"
                   for r in status["recovery"]["evictions"])
    finally:
        server.close()


def test_eviction_wakes_parked_gate_wait_with_typed_error():
    c = StalenessController(num_workers=2, staleness=1)
    c.start_step(0, timeout=1)
    c.finish_step(0)    # worker 0 now AT the bound (worker 1 at 0)
    result = {}

    def parked():
        try:
            c.start_step(0, timeout=30)
        except BaseException as e:       # noqa: BLE001 — recorded for assert
            result["error"] = e
    t = threading.Thread(target=parked)
    t.start()
    time.sleep(0.1)          # let it park (bounded)
    c.retire(0)              # evict the PARKED worker: its RPC must fail NOW
    t.join(timeout=5)
    assert not t.is_alive()
    assert isinstance(result.get("error"), WorkerEvicted)
    # Entry case: an already-retired worker's start_step raises immediately.
    with pytest.raises(WorkerEvicted):
        c.start_step(0, timeout=1)
    # And a register re-admits it (the rejoin path's first half).
    c.register(0)
    c.start_step(0, timeout=1)
    c.finish_step(0)


# --------------------------------------------------- rejoin + crash recovery

def test_remote_worker_auto_rejoins_after_eviction():
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    batch = _ps_data()
    runner = _ps_session(num_workers=2, staleness=2)
    server = PSServer(runner, host="127.0.0.1", watchdog=False)
    host, port = server.address
    rejoined0 = telemetry.counter("recover.rejoined").value
    remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=1)
    try:
        remote.step(batch, timeout=10)
        # Chief-side eviction mid-run (what the watchdog does on a stall).
        recovery.evict(runner.controller, 1, kind="stall", age_s=42.0)
        # The next step hits WorkerEvicted, auto-rejoins seeded at the
        # slowest LIVE count, catches up over read_min, and completes.
        remote.step(batch, timeout=10)
        assert runner.service.updates_applied == 2
        assert telemetry.counter("recover.rejoined").value > rejoined0
        # The catch-up pull re-read live params (the cache was dropped at
        # rejoin, so a stale pre-eviction tree can never be revalidated).
        assert remote.last_version_read >= 1
    finally:
        remote.close()
        server.close()


def test_crash_respawn_readmin_catchup_bit_identical():
    """A worker crash mid-run + replacement with live-param catch-up must
    continue BIT-IDENTICALLY vs an unfailed run (single sequential pusher —
    the regime where async semantics allow exact comparison)."""
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    batches = [_ps_data(seed=s) for s in range(6)]

    def run_leg(crash_at):
        runner = _ps_session(num_workers=1, staleness=0)
        server = PSServer(runner, host="127.0.0.1", watchdog=False)
        host, port = server.address
        if crash_at is not None:
            faults.install(f"worker_crash@step={crash_at},worker=0")
        worker = RemotePSWorker(f"{host}:{port}", runner, worker_id=0,
                                overlap=False)
        i = 0
        try:
            while i < len(batches):
                try:
                    worker.step(batches[i], timeout=10)
                    i += 1
                except faults.WorkerCrashed:
                    # The "coordinator respawn" in miniature: wait for the
                    # server to retire the dead connection, then a fresh
                    # RemotePSWorker re-registers and catches up over
                    # read_min — the crashed step i is retried by the
                    # replacement (it never reached the chief).
                    deadline = time.time() + 10
                    while 0 not in runner.controller._retired \
                            and time.time() < deadline:
                        time.sleep(0.02)
                    worker = RemotePSWorker(f"{host}:{port}", runner,
                                            worker_id=0, overlap=False)
        finally:
            faults.clear()
            worker.close()
            server.close()
        assert runner.service.updates_applied == len(batches)
        return jax.device_get(
            jax.tree_util.tree_leaves(runner.service.state.params))

    clean = run_leg(None)
    crashed = run_leg(3)
    assert all(np.array_equal(a, b) for a, b in zip(clean, crashed))
    assert all(np.isfinite(np.asarray(l)).all() for l in crashed)


# ------------------------------------------------------------ recover action

def test_nan_recover_rolls_back_finishes_finite_and_bit_identical(ar_runner):
    rollbacks0 = telemetry.counter("recover.rollback").value
    monitor = health.HealthMonitor(health.HealthConfig(action="recover"))
    faults.install("nan_grads@step=5")
    final = train(ar_runner, _params(), _batch, steps=12, log_every=2,
                  health_monitor=monitor)
    faults.clear()
    # (a) The run FINISHED (did not halt) with finite params.
    assert int(final.step) == 12
    leaves = jax.device_get(jax.tree_util.tree_leaves(final.params))
    assert all(np.isfinite(l).all() for l in leaves)
    # (b) Exactly the rollback machinery did it.
    assert telemetry.counter("recover.rollback").value > rollbacks0
    assert recovery.recovery_snapshot()["counts"]["rollbacks"] >= 1
    # (c) A callable source replays the rolled-back steps exactly: the
    # recovered run is BIT-IDENTICAL to a never-faulted one.
    clean = train(ar_runner, _params(), _batch, steps=12, log_every=2)
    a = jax.device_get(jax.tree_util.tree_leaves(final.params))
    b = jax.device_get(jax.tree_util.tree_leaves(clean.params))
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


def test_recover_budget_is_per_incident_not_per_run(ar_runner, monkeypatch):
    """AUTODIST_RECOVER_MAX bounds attempts per INCIDENT: two transient
    anomalies at different steps each get the full budget (progress past
    the earlier one resets the counter) — a long run's widely-spaced
    recoveries must not spend a lifetime cap."""
    monkeypatch.setenv("AUTODIST_RECOVER_MAX", "1")
    monitor = health.HealthMonitor(health.HealthConfig(action="recover"))
    faults.install("nan_grads@step=3;nan_grads@step=8")
    final = train(ar_runner, _params(), _batch, steps=12, log_every=2,
                  health_monitor=monitor)
    faults.clear()
    assert int(final.step) == 12   # both incidents recovered
    leaves = jax.device_get(jax.tree_util.tree_leaves(final.params))
    assert all(np.isfinite(l).all() for l in leaves)


def test_retire_reports_whether_it_acted():
    """retire() returns True only for a live->retired transition — the
    recovery plane's bookkeeping follows it, so a stale-generation no-op or
    a double retire can never book a phantom eviction."""
    c = StalenessController(num_workers=2, staleness=1)
    old_gen = c.generation(1)
    c.register(1)                                   # generation bumps
    assert c.retire(1, generation=old_gen) is False  # stale: ignored
    assert c.retire(1) is True                       # acted
    assert c.retire(1) is False                      # already retired
    # evict() on an already-retired worker books nothing.
    before = telemetry.counter("recover.evicted").value
    assert recovery.evict(c, 1, kind="stall") is None
    assert telemetry.counter("recover.evicted").value == before


def test_recover_max_exhaustion_escalates_to_healthhalt(ar_runner,
                                                        monkeypatch):
    monkeypatch.setenv("AUTODIST_RECOVER_MAX", "2")
    monitor = health.HealthMonitor(health.HealthConfig(action="recover"))
    # A PERSISTENT fault (count=99): every replay re-poisons step 5.
    faults.install("nan_grads@step=5,count=99")
    with pytest.raises(telemetry.HealthHalt) as ei:
        train(ar_runner, _params(), _batch, steps=12, log_every=2,
              health_monitor=monitor)
    faults.clear()
    # The escalation is the EXACT halt type (not the recover subclass),
    # with the live state attached — checkpointable, not discarded.
    assert type(ei.value) is telemetry.HealthHalt
    assert ei.value.state is not None
    assert ei.value.anomalies


def test_recover_before_any_good_boundary_escalates(ar_runner):
    monitor = health.HealthMonitor(health.HealthConfig(action="recover"))
    faults.install("nan_grads@step=0,count=99")   # poisoned from step 0
    with pytest.raises(telemetry.HealthHalt):
        train(ar_runner, _params(), _batch, steps=6, log_every=2,
              health_monitor=monitor)
    faults.clear()


def test_snapshot_ring_bounds_and_checkout_copies():
    copies = []

    def copy_fn(state):
        copies.append(state)
        return dict(state)
    ring = recovery.SnapshotRing(keep=2, copy_fn=copy_fn)
    for step in (2, 4, 6):
        ring.push(step, {"step": step})
    assert len(ring) == 2                      # bounded
    assert ring.newest()[0] == 6
    step, state = ring.checkout()
    assert step == 6 and state == {"step": 6}
    assert state is not ring.newest()[1]       # checkout COPIES
    ring.push(6, {"step": 6, "replayed": True})
    assert len(ring) == 2                      # same-step push replaces
    assert ring.newest()[1]["replayed"]
    # Slow-burn fallback: dropping the suspect newest lands one deeper.
    ring.drop_newest()
    assert ring.checkout()[0] == 4
    ring.drop_newest()
    assert ring.checkout() is None             # empty -> escalation
    ring.drop_newest()                         # idempotent on empty
    assert recovery.SnapshotRing().checkout() is None


def test_alert_recover_action_raises_typed_signal():
    from autodist_tpu.telemetry import alerts as _alerts
    from autodist_tpu.telemetry import history as _history
    assert "recover" in _alerts.ACTIONS and "recover" in health.ACTIONS
    telemetry.gauge("selfheal.test.gauge").set(99.0)
    eng = _alerts.AlertEngine(rules=[_alerts.AlertRule(
        name="selfheal_pin", kind="threshold",
        metric="selfheal.test.gauge", op=">", value=1.0)], action="recover")
    h = _history.MetricsHistory(out_dir="", min_interval_s=0.0, engine=eng)
    with pytest.raises(telemetry.AlertRecover) as ei:
        h.sample()
    # The recover signal IS an AlertHalt (background samplers catch it as
    # one) and train()'s wrapper catches the subclass specifically.
    assert isinstance(ei.value, telemetry.AlertHalt)
    telemetry.gauge("selfheal.test.gauge").set(0.0)


# --------------------------------------------------------- coordinator policy

class _FakeProc:
    def __init__(self, code):
        self._code = code

    def wait(self, timeout=None):
        return self._code


def test_coordinator_respawn_policy_budget_and_bookkeeping(monkeypatch):
    from autodist_tpu.coordinator import Coordinator
    monkeypatch.setenv("AUTODIST_WORKER_FAILURE", "respawn")
    monkeypatch.setenv("AUTODIST_RECOVER_MAX", "2")
    respawned = []

    class FakeCluster:
        def remote_exec(self, cmd, address, env=None):
            respawned.append((address, tuple(cmd)))
            return _FakeProc(0)   # the respawned worker exits clean

    coord = Coordinator.__new__(Coordinator)
    coord._cluster = FakeCluster()
    coord._procs = []
    coord._watchdogs = []
    coord._launch_specs = {"10.0.0.2": {"cmd": ["prog"], "env": {"E": "1"},
                                        "respawns": 0}}
    coord.RESPAWN_BACKOFF_S = 0.01
    coord.RESPAWN_BACKOFF_CAP_S = 0.05
    respawns0 = telemetry.counter("recover.respawn").value
    # A nonzero exit respawns the EXACT launch spec instead of killing the
    # chief (the fake proc exits 0, so the chain stops there).
    coord._on_worker_failure("10.0.0.2", 1)
    for w in coord._watchdogs:
        w.join(timeout=5)
    assert respawned == [("10.0.0.2", ("prog",))]
    assert coord._launch_specs["10.0.0.2"]["respawns"] == 1
    assert telemetry.counter("recover.respawn").value == respawns0 + 1
    # Budget exhaustion: _respawn refuses (the caller escalates to halt —
    # os._exit is not testable in-process, the refusal is the decision).
    coord._launch_specs["10.0.0.2"]["respawns"] = 2
    assert coord._respawn("10.0.0.2", 1) is False
    # An address this coordinator never launched refuses too.
    assert coord._respawn("10.9.9.9", 1) is False


def test_coordinator_halt_policy_is_default(monkeypatch):
    from autodist_tpu.coordinator import Coordinator
    monkeypatch.delenv("AUTODIST_WORKER_FAILURE", raising=False)
    assert str(const.ENV.AUTODIST_WORKER_FAILURE.val) == "halt"
    # The overridable seam tests rely on keeps its signature.
    killed = []

    class TestCoordinator(Coordinator):
        def _on_worker_failure(self, address, code):
            killed.append((address, code))
    coord = TestCoordinator.__new__(TestCoordinator)
    coord._on_worker_failure("a", 2)
    assert killed == [("a", 2)]


# ------------------------------------------------------- status + consoles

def test_status_recovery_section_schema_and_console_rendering():
    import importlib.util
    import os as _os
    server, addr = _loopback_stub()
    stub = server._runner
    try:
        recovery.evict(stub.controller, 1, kind="stall", age_s=7.0)
        stub.add_worker(1)    # rejoin
        status = server.status_snapshot()
        rec = status["recovery"]
        assert set(rec) == {"evictions", "rejoins", "rollbacks", "respawns",
                            "counts", "generations"}
        assert rec["counts"]["evicted"] >= 1
        assert rec["counts"]["rejoined"] >= 1
        assert rec["generations"].get(1, 0) >= 1
        # The rename-not-alias contract survives the new section.
        assert "anomalies" not in status
        import json
        json.dumps(status)    # wire-encodable: plain data only
        # adtop renders a recover line; adfleet's row carries the compact
        # fingerprint (both read the same section).
        root = _os.path.join(_os.path.dirname(__file__), _os.pardir, "tools")
        spec = importlib.util.spec_from_file_location(
            "adtop_selfheal", _os.path.join(root, "adtop.py"))
        adtop = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(adtop)
        out = adtop.render(status, addr)
        assert "recover" in out and "evicted" in out and "rejoined" in out
        spec = importlib.util.spec_from_file_location(
            "adfleet_selfheal", _os.path.join(root, "adfleet.py"))
        adfleet = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(adfleet)
        row = adfleet._row(addr, status)
        assert "recov E" in row
    finally:
        server.close()


# ------------------------------------------------------------------ flags

def test_new_flags_registered_and_typed(monkeypatch):
    for name in ("AUTODIST_EVICT_AFTER_S", "AUTODIST_WORKER_FAILURE",
                 "AUTODIST_RECOVER_MAX", "AUTODIST_WIRE_RETRIES",
                 "AUTODIST_WIRE_BACKOFF_S", "AUTODIST_FAULTS"):
        assert name in const.KNOWN_FLAGS
        assert hasattr(const.ENV, name)
    monkeypatch.setenv("AUTODIST_EVICT_AFTER_S", "45.5")
    assert const.ENV.AUTODIST_EVICT_AFTER_S.val == 45.5
    assert recovery.evict_after_s() == 45.5
    monkeypatch.delenv("AUTODIST_EVICT_AFTER_S")
    assert recovery.evict_after_s() is None    # 0/unset = policy off
    monkeypatch.setenv("AUTODIST_RECOVER_MAX", "7")
    assert const.ENV.AUTODIST_RECOVER_MAX.val == 7
    assert recovery.recover_max() == 7
    monkeypatch.setenv("AUTODIST_WIRE_RETRIES", "4")
    assert const.ENV.AUTODIST_WIRE_RETRIES.val == 4
    monkeypatch.setenv("AUTODIST_WIRE_BACKOFF_S", "0.5")
    assert const.ENV.AUTODIST_WIRE_BACKOFF_S.val == 0.5
    assert const.ENV.AUTODIST_WORKER_FAILURE.val == "halt"
    assert const.ENV.AUTODIST_FAULTS.val == ""
