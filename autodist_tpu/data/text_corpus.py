"""Text-corpus ingestion: whitespace-tokenized files -> token shards.

The reference's lm1b pipeline consumed the REAL 1B-word-benchmark corpus
(``examples/lm1b/lm1b_train.py:26-50``): text lines split on whitespace,
flattened into one continuous word stream, cut into ``num_steps``(+1)-token
windows, with word->id lookup through the published vocab file
(``1b_word_vocab.txt``; ``language_model.py:108-111`` — word in column 0,
out-of-vocabulary words hashed into ``oov_bucket_size`` extra ids).

This module is that ingestion TPU-first: a STREAMING tokenizer that reads the
corpus files once, windows the word stream, and writes ``tokens-*.npy``
shards — the exact files the native ``DataLoader(files=...)`` memory-maps and
``examples/lm1b/lm1b_train.py --data_dir`` trains from. Corpus size is
unbounded: rows are flushed shard-by-shard, nothing materializes beyond one
shard buffer. The vocab side accepts the published file format
(:func:`load_vocab`) or builds one from the corpus by frequency
(:func:`build_vocab`).

OOV hashing uses crc32 (stable across processes/runs — Python's ``hash`` is
salted per process, which would tokenize the same corpus differently on
chief and workers).
"""

import glob as globlib
import os
import zlib
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Union

import numpy as np

from autodist_tpu.utils import logging

PathsSpec = Union[str, Sequence[str]]


class Vocabulary:
    """word -> id mapping with hashed out-of-vocabulary buckets.

    ids ``[0, n_words)`` are the known words; ids ``[n_words,
    n_words + oov_buckets)`` are OOV buckets (crc32 of the word, mod buckets)
    — the reference's ``StaticVocabularyTable`` semantics
    (``language_model.py:108-111``). ``vocab_size`` (= embedding rows needed)
    includes the buckets.
    """

    def __init__(self, words: Sequence[str], oov_buckets: int = 1):
        if oov_buckets < 1:
            raise ValueError("oov_buckets must be >= 1 (unknown words need "
                             "somewhere to go)")
        self._ids: Dict[str, int] = {}
        for w in words:
            # First occurrence wins, like a lookup table built top-down.
            self._ids.setdefault(w, len(self._ids))
        self.n_words = len(self._ids)
        self.oov_buckets = oov_buckets
        self.vocab_size = self.n_words + oov_buckets

    def lookup(self, word: str) -> int:
        wid = self._ids.get(word)
        if wid is not None:
            return wid
        return self.n_words + zlib.crc32(word.encode("utf-8")) % self.oov_buckets

    def __len__(self) -> int:
        return self.vocab_size


def load_vocab(path: str, oov_buckets: int = 1,
               max_size: Optional[int] = None) -> Vocabulary:
    """Read a vocab file — one entry per line, word in the FIRST whitespace
    column (the published ``1b_word_vocab.txt`` carries ``word count`` pairs).
    ``max_size`` truncates to the top entries (the file is frequency-sorted)."""
    words: List[str] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            cols = line.split()
            if not cols:
                continue
            words.append(cols[0])
            if max_size is not None and len(words) >= max_size:
                break
    if not words:
        raise ValueError(f"vocab file {path!r} has no entries")
    return Vocabulary(words, oov_buckets)


def _resolve_paths(files: PathsSpec) -> List[str]:
    if isinstance(files, str):
        paths = sorted(globlib.glob(files)) if any(c in files for c in "*?[") \
            else [files]
    else:
        paths = list(files)
    if not paths:
        raise ValueError(f"no corpus files match {files!r}")
    for p in paths:
        if not os.path.exists(p):
            raise FileNotFoundError(p)
    return paths


def _words(paths: List[str]) -> Iterator[str]:
    for p in paths:
        with open(p, encoding="utf-8") as f:
            for line in f:
                yield from line.split()


def build_vocab(files: PathsSpec, max_size: int,
                oov_buckets: int = 1) -> Vocabulary:
    """Build a frequency-sorted vocabulary from the corpus itself (one
    streaming pass) — for corpora without a published vocab file. Ties break
    by first appearance, so the result is deterministic."""
    if max_size < 1:
        raise ValueError("max_size must be >= 1")
    counts: Dict[str, int] = {}
    for w in _words(_resolve_paths(files)):
        counts[w] = counts.get(w, 0) + 1
    # Python's sort is stable and dict order is insertion order, so sorting by
    # count alone already breaks ties by first appearance.
    top = sorted(counts, key=lambda w: -counts[w])[:max_size]
    return Vocabulary(top, oov_buckets)


def tokenize_to_shards(files: PathsSpec, vocab: Vocabulary, directory: str,
                       seq_len: int, rows_per_shard: int = 1 << 16,
                       stride: Optional[int] = None,
                       key: str = "tokens") -> List[str]:
    """Stream the corpus into ``<key>-NNNNN.npy`` shards of
    ``[rows, seq_len + 1]`` int32 windows under ``directory``; returns the
    shard paths (the ``DataLoader(files=...)`` /
    ``lm1b_train.py --data_dir`` input).

    The word stream is continuous across lines and files (the reference
    flat-mapped lines into one stream before windowing). ``stride`` defaults
    to ``seq_len + 1`` — contiguous non-overlapping windows, every token
    trained on once per epoch; ``stride=1`` reproduces the reference's
    every-word-starts-a-window dataset (``lm1b_train.py:43``), trading disk
    for sample diversity; ``stride > seq_len + 1`` SUBSAMPLES, skipping the
    tokens between windows. A tail shorter than a full window is dropped
    (static shapes only). Memory use is one shard buffer, however large the
    corpus. Pre-existing ``<key>-*.npy`` shards in ``directory`` are swept
    first (re-preparing a smaller corpus must not leave stale shards)."""
    if seq_len < 1:
        raise ValueError("seq_len must be >= 1")
    if rows_per_shard < 1:
        raise ValueError("rows_per_shard must be >= 1")
    width = seq_len + 1
    stride = width if stride is None else stride
    if stride < 1:
        raise ValueError("stride must be >= 1")
    os.makedirs(directory, exist_ok=True)
    for stale in globlib.glob(os.path.join(globlib.escape(directory),
                                           f"{globlib.escape(key)}-*.npy")):
        os.remove(stale)

    paths: List[str] = []
    buf = np.empty((rows_per_shard, width), np.int32)
    n_buf = 0
    window: List[int] = []
    n_rows = 0

    def flush():
        nonlocal n_buf
        if n_buf == 0:
            return
        path = os.path.join(directory, f"{key}-{len(paths):05d}.npy")
        np.save(path, buf[:n_buf])
        paths.append(path)
        n_buf = 0

    skip = 0  # words to drop before the next window starts (stride > width)
    for word in _words(_resolve_paths(files)):
        if skip:
            skip -= 1
            continue
        window.append(vocab.lookup(word))
        if len(window) == width:
            buf[n_buf] = window
            n_buf += 1
            n_rows += 1
            del window[:min(stride, width)]
            skip = stride - width if stride > width else 0
            if n_buf == rows_per_shard:
                flush()
    flush()
    if not paths:
        raise ValueError(
            f"corpus has fewer than seq_len + 1 = {width} words; no windows")
    # Sidecar metadata: the training run is a separate process and must size
    # its embedding to cover every id the shards contain — a too-small --vocab
    # would otherwise fail only when an OOV-bucket id gathers out of range.
    write_meta(directory, vocab_size=vocab.vocab_size, seq_len=seq_len,
               rows=n_rows, stride=stride, oov_buckets=vocab.oov_buckets,
               key=key)
    logging.info("Tokenized corpus -> %d rows of %d tokens across %d shards "
                 "in %s (vocab %d incl. %d OOV bucket(s))", n_rows, width,
                 len(paths), directory, vocab.vocab_size, vocab.oov_buckets)
    return paths


def write_meta(directory: str, *, vocab_size: int, seq_len: int, rows: int,
               stride: int, oov_buckets: int, key: str = "tokens") -> None:
    """Write the shard sidecar (one schema, shared by every shard writer —
    the tokenizer here and e.g. lm1b's synthetic-corpus prep)."""
    import json
    with open(os.path.join(directory, f"{key}-meta.json"), "w") as f:
        json.dump({"vocab_size": vocab_size, "seq_len": seq_len,
                   "rows": rows, "stride": stride,
                   "oov_buckets": oov_buckets}, f, indent=1)


def read_meta(directory: str, key: str = "tokens") -> Optional[dict]:
    """The sidecar metadata :func:`write_meta` wrote (None when the shards
    came from a writer without one)."""
    import json
    path = os.path.join(directory, f"{key}-meta.json")
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)
