"""Cluster trace plane: trace/ping/push_trace wire ops, clock-offset rebase,
straggler watchdog, compile/memory telemetry, offline tracedump merge.

Covers the trace plane end to end (docs/usage/observability.md "Cluster
timeline"): a loopback trace-pull/push round-trip over a numpy-only stub
runner, NTP-offset math and the deterministic known-skew rebase (merged
ordering flips when the offsets say so), the PSServer watchdog flagging a
stalled and a straggling stub worker, `tools/tracedump.py` merging two JSONL
ring dumps, and the satellite pins: `export_chrome_trace(pid=,
clock_offset_ns=)`, `stats_snapshot()` uptime/last-seen, and the per-worker
`host_spans_w<id>.json` trace filename.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window (before test_image_data).
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from autodist_tpu import telemetry
from autodist_tpu.telemetry import cluster as tcluster


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Leave process-global telemetry as found: disabled, empty ring (the
    registry is additive-only and harmless to share)."""
    telemetry.disable()
    telemetry.clear()
    yield
    telemetry.disable()
    telemetry.clear()


def _synthetic_state(worker_id, wall_ns, offset_ns, t0s=(0,), durs=None,
                     name="step"):
    """A hand-built trace blob with a controlled clock — the deterministic-
    skew fixture (real rings in one process share one clock, so skew must be
    fabricated)."""
    n = len(t0s)
    return {
        "v": tcluster.TRACE_STATE_VERSION,
        "pid": 4242, "host": "testhost", "worker_id": worker_id,
        "wall_ns": wall_ns, "perf_ns": 0, "epoch_ns": 0,
        "clock_offset_ns": offset_ns,
        "names": [name], "name_idx": np.zeros(n, np.int32),
        "tids": [11], "tid_idx": np.zeros(n, np.int32),
        "t0_ns": np.asarray(t0s, np.int64),
        "dur_ns": np.asarray(durs if durs is not None else [10] * n, np.int64),
        "args_json": "", "thread_names": {11: "main"},
    }


# --------------------------------------------------------------- blob + rebase

def test_local_trace_state_columnar_and_wire_encodable():
    from autodist_tpu.parallel import wire

    telemetry.enable()
    for i in range(16):
        with telemetry.span("fill", idx=i & 3, obj=object()):
            pass
    with telemetry.span("other"):
        pass
    st = telemetry.local_trace_state(worker_id=5, clock_offset_ns=-7)
    assert sorted(st["names"]) == ["fill", "other"]
    assert len(st["name_idx"]) == len(st["t0_ns"]) == len(st["dur_ns"]) == 17
    assert st["worker_id"] == 5 and st["clock_offset_ns"] == -7
    assert st["name_idx"].dtype == np.int32 and st["t0_ns"].dtype == np.int64
    # Span args ride as ONE JSON string (non-encodable values stringified),
    # so the blob crosses the typed wire verbatim without per-span dict
    # encoding — the `trace`/`push_trace` payload + stall-gate contract.
    args0 = tcluster._parse_args_json(st)[0]
    assert args0["idx"] == 0 and isinstance(args0["obj"], str)
    dec = wire.decode(wire.encode(("ok", st)))[1]
    assert dec["names"] == st["names"]
    np.testing.assert_array_equal(dec["t0_ns"], st["t0_ns"])
    # wall/perf pair sampled together: a span's wall-clock start derived from
    # it lands within the snapshot's own lifetime.
    assert abs(st["wall_ns"] - time.time_ns()) < 60e9


def test_ntp_offset_median_and_uncertainty():
    # Midpoint offsets: 160-110=50, 155-105=50, 170-120=50 → all agree;
    # uncertainty = best RTT / 2 = 20 / 2.
    assert tcluster.ntp_offset([(100, 160, 120), (90, 155, 120),
                                (100, 170, 140)]) == (50, 10)
    # One wildly delayed exchange must not move the median.
    off, err = tcluster.ntp_offset(
        [(0, 50, 20), (0, 50, 20), (0, 9_000_000, 8_000_000)])
    assert off == 40 and err == 10
    with pytest.raises(ValueError):
        tcluster.ntp_offset([])


def test_known_skew_rebase_flips_merged_ordering(tmp_path):
    """The deterministic skew pin: worker B's raw wall clock is 1s AHEAD of
    worker A's, but the estimated offsets say B's clock runs 1.8s fast —
    after rebasing, B's span must come FIRST in the merged timeline."""
    a = _synthetic_state(0, wall_ns=1_000_000_000, offset_ns=500_000_000)
    b = _synthetic_state(1, wall_ns=2_000_000_000, offset_ns=-800_000_000)
    path = str(tmp_path / "merged.json")
    assert tcluster.merge_trace_states([a, b], path) == path
    doc = json.load(open(path))
    xs = {ev["pid"]: ev["ts"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
    # pid lanes: worker 0 -> 1, worker 1 -> 2. Rebased starts: A = 1.5s,
    # B = 1.2s → B at origin (ts 0), A 300ms later.
    assert set(xs) == {1, 2}
    assert xs[2] == 0.0
    assert xs[1] == pytest.approx(300_000.0)  # µs
    labels = {ev["pid"]: ev["args"]["name"] for ev in doc["traceEvents"]
              if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert "worker 0" in labels[1] and "worker 1" in labels[2]


def test_merge_rejects_unknown_blob_version(tmp_path):
    bad = _synthetic_state(0, 0, 0)
    bad["v"] = tcluster.TRACE_STATE_VERSION + 1
    with pytest.raises(ValueError, match="version"):
        tcluster.merge_trace_states([bad], str(tmp_path / "x.json"))


# ---------------------------------------------------------- loopback transport

class _StubPSRunner:
    """The minimal surface PSServer._dispatch drives, over a numpy-only
    ParameterService — a real gate and service without model compilation."""

    def __init__(self, num_workers=1, staleness=2):
        from autodist_tpu.parallel.staleness import (ParameterService,
                                                     StalenessController)
        from autodist_tpu.runner import TrainState
        state = TrainState(step=np.zeros((), np.int32),
                           params={"w": np.ones((64,), np.float32)},
                           opt_state=(), ef_state=())
        self.service = ParameterService(state, lambda s, grads: s)
        self.controller = StalenessController(num_workers,
                                              staleness=staleness)

    def add_worker(self, worker_id=None, with_generation=False):
        wid, gen = self.controller.register_with_generation(worker_id)
        handle = type("H", (), {"worker_id": wid})()
        return (handle, gen) if with_generation else handle


def _loopback(num_workers=1, staleness=2, **server_kw):
    from autodist_tpu.parallel.ps_transport import PSServer
    server = PSServer(_StubPSRunner(num_workers, staleness),
                      host="127.0.0.1", **server_kw)
    return server, "%s:%d" % server.address


def test_trace_pull_and_push_roundtrip_over_loopback(tmp_path):
    from autodist_tpu.parallel.ps_transport import RemotePSWorker

    telemetry.enable()
    server, addr = _loopback(watchdog=False)
    remote = RemotePSWorker(addr, runner=None, worker_id=0, overlap=False)
    try:
        offset, err = remote.estimate_clock_offset()
        # Loopback to the same process: the true offset is 0 and the NTP
        # midpoint error is RTT-bounded — far under 50ms even on a loaded box.
        assert abs(offset) < 50_000_000
        assert err >= 0
        assert remote.clock_offset_ns == offset

        with telemetry.span("pull.me", tag=1):
            pass
        blob = remote.trace()
        assert "pull.me" in blob["names"]          # the chief's ring, pulled
        assert blob["worker_id"] is None

        pushed = remote.push_trace()
        assert pushed >= 1
        deposited = server.worker_traces()
        assert set(deposited) == {0}
        assert deposited[0]["worker_id"] == 0
        assert deposited[0]["clock_offset_ns"] == offset

        path = str(tmp_path / "cluster.json")
        assert telemetry.collect_cluster_trace(path, server=server) == path
        doc = json.load(open(path))
        pids = {ev["pid"] for ev in doc["traceEvents"]}
        assert {0, 1} <= pids                      # chief lane + worker lane
        assert any(ev["ph"] == "X" for ev in doc["traceEvents"])
    finally:
        remote.close()
        server.close()


def test_stats_snapshot_gains_uptime_and_last_seen():
    from autodist_tpu.parallel.ps_transport import RemotePSWorker

    server, addr = _loopback(watchdog=False)
    remote = RemotePSWorker(addr, runner=None, worker_id=0, overlap=False)
    try:
        remote._client.call("start_step", 0, 5.0)
        remote._client.call("finish_step", 0)
        snap = remote.stats()
        assert snap["uptime_s"] >= 0.0
        assert isinstance(snap["anomalies"], list)
        assert snap["per_worker"][0]["last_seen_s"] >= 0.0
        assert snap["per_worker"][0]["last_seen_s"] <= snap["uptime_s"] + 1.0
        json.dumps(snap)                  # crossed the wire: plain data
    finally:
        remote.close()
        server.close()


def test_watchdog_flags_stalled_worker():
    from autodist_tpu.parallel.ps_transport import RemotePSWorker

    server, addr = _loopback(watchdog=True, watchdog_interval=0.05)
    remote = RemotePSWorker(addr, runner=None, worker_id=0, overlap=False)
    try:
        flags = telemetry.registry().counter("ps.straggler.flags")
        before = flags.value
        remote._client.call("start_step", 0, 5.0)
        remote._client.call("finish_step", 0)
        # Go silent: after ~3 intervals the watchdog must flag worker 0.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and 0 not in server._watchdog.flagged:
            time.sleep(0.02)
        assert 0 in server._watchdog.flagged
        assert flags.value > before
        assert telemetry.registry().gauge(
            "ps.worker.last_seen_s.w0").value > 0.0
        kinds = {ev["name"] for ev in telemetry.events()
                 if ev.get("worker") == 0}
        assert "ps.anomaly.stall" in kinds
    finally:
        remote.close()
        server.close()


def test_watchdog_names_the_straggler():
    """Two workers, bound 1: worker 1 completes a step and parks at the
    bound; worker 0 never advances — the watchdog must name worker 0 (the
    culprit), not the parked victim."""
    server, addr = _loopback(num_workers=2, staleness=1,
                             watchdog=True, watchdog_interval=60.0)
    try:
        runner = server._runner
        runner.controller.register(0)
        runner.controller.register(1)
        server._stats_for(0)
        server._stats_for(1)
        runner.controller.finish_step(1)    # worker 1 now AT the bound
        # Deterministic direct ticks. One instant at the bound is normal
        # steady-state gating — the flag needs STALL_INTERVALS consecutive
        # ticks of persistence before it fires.
        server._watchdog._sample()
        assert server._watchdog.flagged == set()
        for _ in range(int(server._watchdog.STALL_INTERVALS) - 1):
            server._watchdog._sample()
        assert server._watchdog.flagged == {0}
        # The culprit catching up clears the condition AND the persistence
        # counter — the next bound-parked instant starts from zero again.
        runner.controller.finish_step(0)
        server._watchdog._sample()
        assert server._watchdog.flagged == set()
        assert server._watchdog._straggler_ticks == {}
        # A retired worker leaves the stall scan entirely: its frozen
        # last-seen age must not flag it forever after a clean departure.
        with server._worker_stats_lock:
            server._worker_stats[1].last_seen = time.monotonic() - 9999.0
        runner.controller.retire(1)
        server._watchdog._sample()
        assert 1 not in server._watchdog.flagged
        kinds = {ev["name"] for ev in telemetry.events()
                 if ev.get("worker") == 0}
        assert "ps.anomaly.straggler" in kinds
    finally:
        server.close()


def test_live_lags_and_bound():
    from autodist_tpu.parallel.staleness import StalenessController
    c = StalenessController(3, staleness=2)
    assert c.bound == 2
    c.finish_step(0)
    c.finish_step(0)
    c.finish_step(1)
    assert c.live_lags() == {0: 2, 1: 1, 2: 0}
    c.retire(2)
    assert c.live_lags() == {0: 1, 1: 0}


# ----------------------------------------------------------- offline tracedump

def _tracedump():
    spec = importlib.util.spec_from_file_location(
        "tracedump_cli", os.path.join(os.path.dirname(__file__), os.pardir,
                                      "tools", "tracedump.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tracedump_merges_two_jsonl_rings(tmp_path):
    telemetry.enable()
    with telemetry.span("ring.a", n=1):
        pass
    p0 = str(tmp_path / "w0.jsonl")
    telemetry.dump_spans_jsonl(p0, worker_id=0)
    telemetry.clear()
    with telemetry.span("ring.b"):
        pass
    p1 = str(tmp_path / "w1.jsonl")
    telemetry.dump_spans_jsonl(p1, worker_id=1, clock_offset_ns=1000)

    # JSONL round-trips losslessly (incl. the offset override hook).
    st = telemetry.load_trace_jsonl(p1)
    assert st["worker_id"] == 1 and st["clock_offset_ns"] == 1000
    assert telemetry.load_trace_jsonl(p1, clock_offset_ns=5)[
        "clock_offset_ns"] == 5

    out = str(tmp_path / "merged.json")
    td = _tracedump()
    assert td.merge_dumps(out, [p0, p1], offsets={1: 2000}) == out
    doc = json.load(open(out))
    by_pid = {}
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X":
            by_pid.setdefault(ev["pid"], []).append(ev["name"])
    assert set(by_pid) == {1, 2}           # one lane per worker id
    assert by_pid[1] == ["ring.a"] and by_pid[2] == ["ring.b"]
    # CLI argv plumbing (in-process main(), no subprocess).
    assert td.main([str(tmp_path / "cli.json"), p0, p1,
                    "--offset", "1:2000"]) == 0
    assert json.load(open(tmp_path / "cli.json"))["traceEvents"]


def test_tracedump_rejects_non_dump_input(tmp_path):
    bad = tmp_path / "notadump.jsonl"
    bad.write_text('["just", "a", "row"]\n')
    with pytest.raises(ValueError, match="meta"):
        telemetry.load_trace_jsonl(str(bad))


# -------------------------------------------------- export params + filenames

def test_export_chrome_trace_pid_and_offset_params(tmp_path):
    telemetry.enable()
    with telemetry.span("shifted"):
        pass
    base = json.load(open(telemetry.export_chrome_trace(
        str(tmp_path / "a.json"))))
    moved = json.load(open(telemetry.export_chrome_trace(
        str(tmp_path / "b.json"), pid=77, clock_offset_ns=2_000_000)))
    ev0 = next(e for e in base["traceEvents"] if e["ph"] == "X")
    ev1 = next(e for e in moved["traceEvents"] if e["ph"] == "X")
    assert ev0["pid"] == os.getpid() and ev1["pid"] == 77
    assert all(e["pid"] == 77 for e in moved["traceEvents"])   # M events too
    assert ev1["ts"] - ev0["ts"] == pytest.approx(2000.0)      # ns -> µs
    assert ev1["dur"] == ev0["dur"]


def test_trace_writes_per_worker_host_span_file(tmp_path):
    from autodist_tpu import const
    from autodist_tpu.utils import tracing
    with tracing.trace("cluster_t", trace_dir=str(tmp_path),
                       with_host_spans=True):
        with telemetry.span("in.window"):
            pass
    wid = const.ENV.AUTODIST_PROCESS_ID.val
    path = tmp_path / f"host_spans_w{wid}.json"
    assert path.exists()
    names = [e["name"] for e in json.load(open(path))["traceEvents"]
             if e["ph"] == "X"]
    assert "in.window" in names


# ------------------------------------------------------ compile/memory gauges

def test_compile_signature_and_probe_counters():
    """The runner-side compile telemetry, without compiling anything: a new
    dispatch signature routes through _CompileProbe (bumping jit.cache_miss
    and jit.compile_s), a repeated one returns a plain span."""
    from autodist_tpu.runner import DistributedRunner, _CompileProbe

    import weakref

    telemetry.enable()
    r = DistributedRunner.__new__(DistributedRunner)   # no mesh/model needed
    r._compile_sigs = set()
    r._fetch_tokens = weakref.WeakKeyDictionary()
    r._fetch_token_next = 0
    batch = {"x": np.zeros((4, 2), np.float32)}
    misses = telemetry.counter("jit.cache_miss")
    secs = telemetry.counter("jit.compile_s")
    before, before_s = misses.value, secs.value

    cm = r._dispatch_span("runner.run.dispatch", "step", None, batch)
    assert isinstance(cm, _CompileProbe)
    with cm:
        time.sleep(0.002)
    assert misses.value == before + 1
    assert secs.value > before_s

    again = r._dispatch_span("runner.run.dispatch", "step", None, batch)
    assert not isinstance(again, _CompileProbe)        # cached signature
    assert misses.value == before + 1
    # A different shape is a new signature -> a new probe.
    other = r._dispatch_span("runner.run.dispatch", "step", None,
                             {"x": np.zeros((8, 2), np.float32)})
    assert isinstance(other, _CompileProbe)
    # jit.compile spans carry the signature digest.
    jc = [s for s in telemetry.snapshot_spans() if s[0] == "jit.compile"]
    assert jc and "sig" in jc[-1][4]

    # Fetch-fn tokens are never reused: a new fn after the old one died
    # gets a fresh token (a recycled id() would alias the signatures).
    f1 = lambda p, b: p  # noqa: E731
    tok1 = r._fetch_token(f1)
    del f1
    f2 = lambda p, b: b  # noqa: E731
    assert r._fetch_token(f2) != tok1

    telemetry.disable()
    null = r._dispatch_span("runner.run.dispatch", "step", None, batch)
    from autodist_tpu.telemetry.spans import _NULL_SPAN
    assert null is _NULL_SPAN                          # disabled: no-op CM


def test_sample_device_memory_sets_gauges():
    telemetry.enable()
    keep = np.ones(8)     # host array; live_arrays() counts jax arrays only
    import jax
    dev = jax.device_put(np.ones((16,), np.float32))
    n = telemetry.sample_device_memory()
    assert n >= 2
    snap = telemetry.snapshot()
    assert snap["device.live_buffers"] >= 1
    assert snap["device.live_bytes"] >= dev.nbytes
    del keep, dev
