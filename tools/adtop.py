#!/usr/bin/env python
"""adtop — a top-style live console for autodist servers.

Polls any PSServer or InferenceServer address over the ``status`` wire opcode
and renders one screen: uptime and throughput counters, a per-worker table
(last-seen age, instantaneous staleness lag, gate-entry lag histogram, wire
traffic) for training endpoints, the queue/batch/in-flight-request table for
serving endpoints, the attribution plane's ``train.mfu``/``train.membw_util``
and ``train.attr.*`` phase-share gauges when profiling is on, the
``train.health.*`` gauges when the health monitors are on, and the most
recent anomaly events (watchdog stalls/stragglers, health NaN/spike
records).

Usage:
    python tools/adtop.py HOST:PORT                # live screen, 2s refresh
    python tools/adtop.py HOST:PORT --interval 5
    python tools/adtop.py HOST:PORT --once         # one plain-text snapshot
    python tools/adtop.py HOST:PORT --raw          # one raw JSON snapshot

With no address, ``AUTODIST_PS_ADDR`` then ``AUTODIST_SERVE_ADDR`` is tried.
``--once``/``--raw`` are what headless boxes, scripts, and the tests use; the
live screen needs only ANSI clear-home (no curses dependency), so it works in
any terminal the training job's logs already scroll through.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def fetch_status(address, timeout: float = 10.0) -> dict:
    """One ``status`` request against ``address`` (``host:port`` or a
    ``(host, port)`` tuple); raises ConnectionError/PSClientError on an
    unreachable or pre-``status`` server. ``timeout`` bounds the reply wait
    too — a hung-but-accepting server must error a console poll, not park
    it forever."""
    from autodist_tpu.parallel.ps_transport import _PSClient
    client = _PSClient(address, connect_timeout=timeout,
                       read_timeout=timeout)
    try:
        return client.call("status")[0]
    finally:
        client.close()


def _fmt_age(seconds) -> str:
    seconds = float(seconds)
    if seconds < 120:
        return f"{seconds:.1f}s"
    if seconds < 7200:
        return f"{seconds / 60:.1f}m"
    return f"{seconds / 3600:.1f}h"


def _hist_quantile(hist: dict, q: float):
    """The SHARED bucket-interpolating estimator
    (:func:`autodist_tpu.telemetry.metrics.quantile`) — the alert engine's
    burn-rate predicate and adfleet's aggregation use the same one, so no
    two consoles can disagree on what p99 means."""
    from autodist_tpu.telemetry import metrics as _metrics
    return _metrics.quantile(hist, q)


def _counter(reg: dict, name: str):
    v = reg.get(name)
    return v if isinstance(v, (int, float)) else None


def _perf_lines(reg: dict) -> list:
    """The attribution plane's roofline + phase-share gauges, one line:
    ``perf     mfu 28.3%  membw 41.2%  attr comp .61 comm .05 host .22
    data .07 rb .05`` (only the gauges the run booked; the share rendering
    is profiling.format_shares — the same one the train: log line uses)."""
    from autodist_tpu.telemetry import profiling
    head = []
    mfu = reg.get("train.mfu")
    if isinstance(mfu, (int, float)):
        head.append(f"mfu {100.0 * mfu:.1f}%")
    bw = reg.get("train.membw_util")
    if isinstance(bw, (int, float)):
        head.append(f"membw {100.0 * bw:.1f}%")
    shares = {phase: reg.get(f"train.attr.{phase}")
              for phase in profiling.ATTR_PHASES
              if isinstance(reg.get(f"train.attr.{phase}"), (int, float))}
    if shares:
        head.append("attr " + profiling.format_shares(shares))
    return ["perf     " + "  ".join(head)] if head else []


def _req_lines(reg: dict, alerts: dict) -> list:
    """The serving request-attribution line: per-scheduler-round
    wire/queue/prefill/decode shares (``serve.attr.*`` — the serving mirror
    of ``train.attr.*``, same sum-to-1.0 contract), plus the exemplar rid
    when a firing alert carries one — the alert names a concrete request and
    this line says where to look (``tools/adtrace.py`` renders it)."""
    phases = ("wire", "queue", "prefill", "decode")
    shares = {p: reg.get(f"serve.attr.{p}") for p in phases
              if isinstance(reg.get(f"serve.attr.{p}"), (int, float))}
    if not shares:
        return []
    line = "req      attr " + " ".join(
        f"{p} {shares[p]:.2f}".replace(" 0.", " .")
        for p in phases if p in shares)
    for a in (alerts.get("active") or []):
        ex = a.get("exemplar")
        if isinstance(ex, dict) and ex.get("rid") is not None:
            line += f"  exemplar {ex['rid']} ({a.get('rule', '?')})"
            break
    return [line]


def _health_lines(reg: dict) -> list:
    rows = [(k.split("train.health.", 1)[1], v) for k, v in sorted(reg.items())
            if k.startswith("train.health.") and isinstance(v, (int, float))]
    if not rows:
        return []
    return ["health   " + "  ".join(f"{name} {value:.4g}"
                                    for name, value in rows)]


def _event_lines(events, limit: int = 5) -> list:
    out = []
    for rec in list(events)[-limit:]:
        rec = dict(rec)
        name = rec.pop("name", "event")
        t_wall = rec.pop("t_wall_s", None)
        when = time.strftime("%H:%M:%S", time.localtime(t_wall)) \
            if t_wall else "--:--:--"
        fields = " ".join(f"{k}={v}" for k, v in sorted(rec.items()))
        out.append(f"  {when}  {name}  {fields}")
    return out


def _alert_detail(a: dict) -> str:
    """The numbers that tripped one active-alert record, as ``k=v`` pairs —
    ONE formatter shared with ``tools/adfleet.py`` (like the quantile
    helper: two consoles must read an alert record identically)."""
    return " ".join(f"{k}={v}" for k, v in sorted(a.items())
                    if k not in ("rule", "fired_t_wall_s", "for_s"))


def _alert_line(a: dict, where: str = "") -> str:
    """One active alert as one console line (``where`` splices a fleet
    endpoint in) — the layout itself is shared, not just the detail."""
    return (f"  {a.get('rule', '?'):<18} firing "
            f"{_fmt_age(a.get('for_s', 0))}{where}  {_alert_detail(a)}")


def _alert_lines(alerts: dict) -> list:
    """The status payload's ``alerts`` section: one line per ACTIVE firing
    (rule, how long, the numbers that tripped it), plus a recently-resolved
    count. Nothing when the alert plane never armed (rules == 0)."""
    active = alerts.get("active") or []
    resolved = alerts.get("resolved") or []
    if not active and not resolved:
        return []
    out = [f"alerts   {len(active)} active, {len(resolved)} recently "
           f"resolved (action {alerts.get('action') or '?'})"]
    for a in active:
        out.append(_alert_line(a))
    return out


def _recovery_lines(status: dict) -> list:
    """The status payload's ``recovery`` section: one summary line of action
    counts (evictions/rejoins/rollbacks/respawns) plus the newest record per
    non-empty category. Nothing when the runtime never acted — the healthy
    screen stays unchanged."""
    rec = status.get("recovery") or {}
    counts = rec.get("counts") or {}
    if not any(counts.values()):
        return []
    head = "  ".join(f"{name} {counts[name]}"
                     for name in ("evicted", "rejoined", "rollbacks",
                                  "respawns") if counts.get(name))
    gens = rec.get("generations") or {}
    if gens:
        head += "  gen " + ",".join(f"w{w}:{g}" for w, g in gens.items())
    out = [f"recover  {head}"]
    for label, key in (("evicted", "evictions"), ("rejoined", "rejoins"),
                       ("rollback", "rollbacks"), ("respawn", "respawns")):
        records = rec.get(key) or []
        if records:
            last = dict(records[-1])
            last.pop("t_wall_s", None)
            fields = " ".join(f"{k}={v}" for k, v in sorted(last.items()))
            out.append(f"  last {label}: {fields}")
    return out


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _memory_lines(status: dict) -> list:
    """The status payload's ``memory`` section (the memory plane's census):
    live bytes + pressure against the booked budget, then one owner=bytes
    pair per non-zero census owner (``other`` is the unclaimed residual —
    the leak-hunting number). Nothing while the plane never armed — the
    healthy screen stays unchanged."""
    mem = status.get("memory") or {}
    owned = mem.get("owned") or {}
    if not owned and not mem.get("live_bytes"):
        return []
    head = (f"mem      live {_fmt_bytes(mem.get('live_bytes'))}  "
            f"pressure {mem.get('pressure', 0.0):.2f}")
    if mem.get("budget_bytes"):
        head += (f" (budget {_fmt_bytes(mem['budget_bytes'])}, "
                 f"{mem.get('budget_source') or '?'})")
    out = [head]
    pairs = "  ".join(f"{owner} {_fmt_bytes(n)}"
                      for owner, n in sorted(owned.items()) if n)
    if pairs:
        out.append(f"  owned  {pairs}")
    return out


def _staleness_compact(hist: dict) -> str:
    body = ",".join(f"{k[3:]}:{n}" for k, n in hist.items()
                    if k.startswith("le:") and n)
    return "{" + body + "}"


def render(status: dict, address: str = "") -> str:
    """One plain-text screen for a ``status`` payload (PS or serving kind) —
    the single rendering path behind ``--once`` and the live loop, so tests
    pin exactly what operators see."""
    kind = status.get("kind", "?")
    reg = status.get("registry", {}) or {}
    lines = [f"adtop — {kind} server {address}  "
             f"up {_fmt_age(status.get('uptime_s', 0))}  "
             f"{time.strftime('%H:%M:%S')}"]
    if status.get("error"):
        # A failed poll (live loop) must say WHY on screen, not silently
        # blank the tables — the operator needs refused-vs-timeout-vs-dead.
        lines.append(f"ERROR    {status['error']}")
    wire = status.get("wire") or {}
    if wire:
        lines.append(f"wire     tx {wire.get('bytes_sent', 0):,}B/"
                     f"{wire.get('msgs_sent', 0)}msg  "
                     f"rx {wire.get('bytes_received', 0):,}B/"
                     f"{wire.get('msgs_received', 0)}msg")
    saved = reg.get("ps.wire.bytes_saved", 0)
    if saved:
        # The push compressor's accounting (in-process workers mirror into
        # this registry; absent — exact wire — the line stays off screen).
        lines.append(f"compress saved {int(saved):,}B  "
                     f"quantized {int(reg.get('ps.wire.bytes_quantized', 0)):,}B"
                     f"  {reg.get('wire.quantize_s', 0.0):.3f}s quantize")
    if kind == "ps":
        bound = status.get("staleness_bound")
        version = status.get("version")
        head = f"gate     bound {bound if bound is not None else 'inf'}"
        if version is not None:
            head += f"  version {version}"
        shards = status.get("shard_versions")
        if shards:
            head += f"  shards {shards}"
        lines.append(head)
        per_worker = status.get("per_worker", {}) or {}
        if per_worker:
            lines.append("worker   last-seen  lag  staleness            wire")
            for wid in sorted(per_worker, key=str):
                w = per_worker[wid]
                seen = _fmt_age(w["last_seen_s"]) \
                    if "last_seen_s" in w else "?"
                lag = w.get("lag", "?")
                stal = _staleness_compact(w.get("staleness", {}) or {})
                wired = w.get("wire") or {}
                lines.append(
                    f"  w{wid:<5} {seen:>9}  {lag!s:>3}  {stal:<20} "
                    f"rx {wired.get('bytes_received', 0):,}B")
    elif kind == "serve":
        cap = status.get("capacity", 0)
        in_flight = status.get("in_flight", []) or []
        lines.append(f"queue    depth {status.get('queue_depth', 0)}  "
                     f"slots {len(in_flight)}/{cap}  "
                     f"mode {status.get('mode', '?')}  "
                     f"engine {status.get('engine', '?')}")
        done = _counter(reg, "serve.requests.completed")
        rej = _counter(reg, "serve.requests.rejected")
        total = reg.get("serve.latency_s.total")
        if isinstance(total, dict):
            p50 = _hist_quantile(total, 0.5)
            p99 = _hist_quantile(total, 0.99)
            lines.append(
                f"slo      done {done or 0}  rejected {rej or 0}  "
                f"p50~ {f'{p50:.4g}' if p50 is not None else '-'}s  "
                f"p99~ {f'{p99:.4g}' if p99 is not None else '-'}s")
        used = _counter(reg, "serve.kv.pages_used")
        free = _counter(reg, "serve.kv.pages_free")
        if used is not None or free is not None:
            # Paged-KV plane (serving/paged.py): pool occupancy + the
            # prefix-cache hit ledger. Absent on a dense-slab engine.
            hits = _counter(reg, "serve.kv.prefix_hits") or 0
            misses = _counter(reg, "serve.kv.prefix_misses") or 0
            lines.append(f"kv       pages {int(used or 0)} used / "
                         f"{int(free or 0)} free  "
                         f"prefix hits {int(hits)} misses {int(misses)}")
        if in_flight:
            lines.append("request  slot   age  tokens  prompt")
            for r in in_flight:
                lines.append(f"  #{r.get('request_id', '?'):<6} "
                             f"{r.get('slot', '?')!s:>4} "
                             f"{_fmt_age(r.get('age_s', 0)):>5}  "
                             f"{r.get('tokens', 0):>6}  "
                             f"{r.get('prompt_len', 0):>6}")
    elif kind == "router":
        routed = _counter(reg, "serve.router.routed") or 0
        shed = _counter(reg, "serve.router.shed") or 0
        replayed = _counter(reg, "serve.router.replayed") or 0
        lines.append(f"router   routed {int(routed)}  shed {int(shed)}  "
                     f"replayed {int(replayed)}")
        replicas = status.get("replicas") or []
        if replicas:
            lines.append("replica              gen  in-flight  queue  state")
            for r in replicas:
                state = "down" if r.get("down") else (
                    "draining" if r.get("draining") else "up")
                lines.append(f"  {r.get('replica', '?'):<18} "
                             f"{r.get('generation', 0)!s:>4} "
                             f"{r.get('in_flight', 0)!s:>10} "
                             f"{r.get('queue_depth', 0)!s:>6}  {state}")
    lines.extend(_perf_lines(reg))
    lines.extend(_req_lines(reg, status.get("alerts") or {}))
    lines.extend(_health_lines(reg))
    lines.extend(_memory_lines(status))
    lines.extend(_alert_lines(status.get("alerts") or {}))
    lines.extend(_recovery_lines(status))
    events = status.get("events") or status.get("anomalies") or []
    if events:
        lines.append(f"events   ({len(events)} recorded, newest last)")
        lines.extend(_event_lines(events))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="adtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("address", nargs="?", default=None,
                    help="server host:port (default: AUTODIST_PS_ADDR, then "
                         "AUTODIST_SERVE_ADDR)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (headless/test mode)")
    ap.add_argument("--raw", action="store_true",
                    help="print one raw JSON status payload and exit")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh seconds for the live screen (default 2)")
    args = ap.parse_args(argv)
    address = args.address
    if address is None:
        from autodist_tpu import const
        address = str(const.ENV.AUTODIST_PS_ADDR.val) \
            or str(const.ENV.AUTODIST_SERVE_ADDR.val)
    if not address:
        print("adtop: no address given and neither AUTODIST_PS_ADDR nor "
              "AUTODIST_SERVE_ADDR is set", file=sys.stderr)
        return 2
    try:
        status = fetch_status(address)
    except Exception as e:
        print(f"adtop: cannot read status from {address}: {e}",
              file=sys.stderr)
        return 1
    if args.raw:
        print(json.dumps(status, default=str, indent=1))
        return 0
    if args.once:
        print(render(status, address))
        return 0
    try:
        while True:
            # ANSI clear + home: a live screen with zero terminal deps.
            sys.stdout.write("\x1b[2J\x1b[H" + render(status, address) + "\n")
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
            try:
                status = fetch_status(address)
            except Exception as e:
                status = {"kind": "?", "error": str(e)}
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
