"""Arbitrary fetches + feed polymorphism (reference remapper.py parity).

The reference fetched any graph tensor with per-kind contraction: train-ops on
all replicas, per-example tensors concatenated, scalars from the master replica
(``remapper.py:125-185``). The SPMD equivalents: ``runner.run(..., fetches=fn)``
computes ``fn(params, batch)`` inside the compiled step; per-example outputs
return as the global (logically concatenated) array, scalars replicated. Feeds:
batches whose leading dim is NOT divisible by the data-parallel size replicate
(every device computes the identical full batch) and stay value-exact.
"""

import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist
from autodist_tpu.strategy import AllReduce, PS

LR = 0.1


def _data(n=16, seed=5):
    rng = np.random.RandomState(seed)
    x = rng.randn(n).astype(np.float32)
    y = (3.0 * x + 2.0).astype(np.float32)
    return {"x": x, "y": y}


def _loss(p, b):
    pred = b["x"] * p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def _session(builder, batch):
    ad = AutoDist(strategy_builder=builder)
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}
    runner = ad.create_distributed_session(_loss, params, optax.sgd(LR),
                                           example_batch=batch)
    return runner, runner.init(params)


def test_fetches_per_example_and_scalar():
    batch = _data()
    runner, state = _session(AllReduce(), batch)

    def fetch(params, b):
        pred = b["x"] * params["w"] + params["b"]
        return {"pred": pred, "mean_abs_err": jnp.mean(jnp.abs(b["y"] - pred))}

    state, (loss, fetched) = runner.run(state, batch, fetches=fetch)
    # Computed from the pre-update params (w=b=0): pred == 0.
    np.testing.assert_allclose(np.asarray(fetched["pred"]), np.zeros(16), atol=1e-7)
    np.testing.assert_allclose(float(fetched["mean_abs_err"]),
                               float(np.mean(np.abs(batch["y"]))), rtol=1e-6)
    assert fetched["pred"].shape == (16,)  # concat contraction: global batch size

    # Second step fetches from the updated params; default fetches still work.
    state, (loss2, fetched2) = runner.run(state, batch, fetches=fetch)
    assert float(fetched2["mean_abs_err"]) < float(fetched["mean_abs_err"])
    state, loss3 = runner.run(state, batch)
    assert float(loss3) < float(loss2)


def test_fetches_work_with_ps_strategy():
    batch = _data()
    runner, state = _session(PS(), batch)
    state, (loss, fetched) = runner.run(
        state, batch, fetches=lambda p, b: p["w"] * 2.0)
    np.testing.assert_allclose(float(fetched), 0.0, atol=1e-7)
    state, (loss, fetched) = runner.run(
        state, batch, fetches=lambda p, b: p["w"] * 2.0)


def test_non_divisible_batch_replicates_and_stays_exact():
    """B=10 over an 8-way dp mesh: the batch replicates (every device computes the
    identical full-batch loss) and the update equals the single-device one."""
    batch = _data(n=10)
    runner, state = _session(AllReduce(), batch)
    state, loss = runner.run(state, batch)
    x, y = batch["x"], batch["y"]
    want_w = -LR * float(np.mean(-2.0 * x * y))
    want_b = -LR * float(np.mean(-2.0 * y))
    np.testing.assert_allclose(float(state.params["w"]), want_w, rtol=1e-5)
    np.testing.assert_allclose(float(state.params["b"]), want_b, rtol=1e-5)
    np.testing.assert_allclose(float(loss), float(np.mean(y ** 2)), rtol=1e-5)


def test_function_api_supports_fetches():
    """ad.function's step callable passes fetches through to the runner."""
    batch = _data()
    from autodist_tpu import AutoDist as AD
    ad = AD(strategy_builder=AllReduce())
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}
    step = ad.function(_loss, params, optax.sgd(LR), example_batch=batch)
    loss0 = step(batch)
    default, fetched = step(batch, fetches=lambda p, b: p["w"] + p["b"])
    assert float(default) < float(loss0)
    assert np.isfinite(float(fetched))
