"""Saver: strategy-independent checkpoints under original parameter names.

Reference parity (``autodist/checkpoint/saver.py``):

- Saves under ORIGINAL single-node names whatever the strategy (``:47-61``): each
  parameter is addressed at its full logical shape — the inverse of the
  reference's ``SaveSliceInfo`` reassembly of partitioned variables
  (``kernel/partitioner.py:251-347``).
- Restoring reshards onto whatever mesh/strategy the reader uses (the reference
  restored a checkpoint into differently-distributed runs or plain TF).
- ``max_to_keep`` rotation and a ``checkpoint`` state file mirror ``tf.train.Saver``
  semantics the reference inherited.
- Multi-process saves work against CROSS-process-sharded state (ZeRO opt state,
  partitioned params): the reference's 2-node NFS saver contract
  (``tests/integration/cases/c10.py:1-12``) — here each process writes the
  shards it owns instead of routing every value through the chief's session.

Two formats, detected on restore:

- **single-file** (v1): one ``<prefix>.npz`` holding ``{name: full ndarray}``
  plus a JSON manifest (``<prefix>.json``). Written by single-process saves;
  always loadable.
- **sharded** (v2): per-process ``<prefix>.shardNNNNN-of-NNNNN.npz`` files plus
  a manifest (``<prefix>.json`` with ``"format": "sharded"``) mapping each
  logical tensor to its index-slices across files — the SaveSliceInfo idea
  done TPU-first. Each distinct shard index is written exactly once, by the
  process holding the lowest-id device for it; the chief publishes the
  manifest only after every writer's file landed (coordination-service
  barrier — host-side RPC, no device collectives in the save path, so a save
  can never interleave with training collectives). Restore assembles full
  logical arrays from any process count, so cross-topology restore works
  (merge-on-restore).

Optimizer state is saved under an ``__opt__/`` prefix, compressor state under
``__ef__/``, the step counter under ``__step__`` (v1) / the manifest (v2).
ZeRO weight-update sharding (``DistributedRunner(zero=...)``) checkpoints
transparently in both formats: single-process saves gather each sharded
optimizer-moment leaf to its full logical shape on the host (``device_get``
assembles addressable shards — gather-on-save), multi-process saves write the
v2 per-shard slices; restore reshards per the READING runner's plan, so an
unsharded checkpoint restores into a ZeRO run and vice versa (pinned by
``tests/test_zero_update.py``). The async-PS sharded service contributes the
same way: its ``state`` property re-assembles per-shard optimizer slices into
the original unsharded structure before the Saver ever sees them.
Writes can be made asynchronous (``async_write=True``): device→host snapshot
happens synchronously, file IO on a background thread, double-buffered (a new
save joins the previous write first).
"""

import glob
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from autodist_tpu.utils import logging

PyTree = Any

_OPT_PREFIX = "__opt__/"
_EF_PREFIX = "__ef__/"
_STEP_KEY = "__step__"
_STATE_FILE = "checkpoint"  # directory-level latest-pointer, like TF's


def _is_sharded_manifest(path: str) -> bool:
    """The one rule for 'this .json is a sharded-checkpoint manifest' —
    shared by scanning, existence checks, and loading, so they can never
    disagree about what counts as a checkpoint."""
    try:
        with open(path) as f:
            return json.load(f).get("format") == "sharded"
    except (ValueError, OSError):
        return False


def _scan_checkpoints(base: str):
    """``[(step, prefix)]`` for every checkpoint on disk, step-ascending — a
    ``<base>-<step>.npz`` single file OR a ``<base>-<step>.json`` sharded
    manifest. The single name-exact filename parse shared by rotation adoption
    and name-filtered latest lookup."""
    found = {}
    for path in glob.glob(glob.escape(base) + "-*.npz"):
        m = re.fullmatch(re.escape(base) + r"-(\d+)\.npz", path)
        if m:
            found[int(m.group(1))] = path[:-len(".npz")]
    for path in glob.glob(glob.escape(base) + "-*.json"):
        m = re.fullmatch(re.escape(base) + r"-(\d+)\.json", path)
        if m and int(m.group(1)) not in found and _is_sharded_manifest(path):
            found[int(m.group(1))] = path[:-len(".json")]
    return sorted(found.items())


def checkpoint_exists(prefix: str) -> bool:
    """True when ``prefix`` names a complete checkpoint (either format)."""
    return os.path.exists(prefix + ".npz") \
        or _is_sharded_manifest(prefix + ".json")


def _read_recorded(save_path: str):
    """The directory-level state file's recorded rotation list (``[]`` when
    missing/corrupt) plus the regex matching THIS name's prefixes — the one
    read/parse shared by rotation adoption and state-file rewriting, so the
    two can never disagree about which entries belong to a name."""
    state_path = os.path.join(os.path.dirname(save_path) or ".", _STATE_FILE)
    recorded = []
    if os.path.exists(state_path):
        try:
            with open(state_path) as f:
                recorded = json.load(f).get("all") or []
        except (ValueError, OSError):
            recorded = []
    return state_path, recorded, re.compile(re.escape(save_path) + r"-\d+")


def _flatten_leaves(tree: PyTree) -> Dict[str, Any]:
    """Flatten a pytree to {original-name: leaf} WITHOUT materializing to host
    — sharded saves must address per-device shards, not full arrays."""
    from autodist_tpu.model_spec import _path_name
    return {_path_name(path): leaf
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}


# ------------------------------------------------------------ sharded format

def _norm_index(idx, shape):
    """Normalize a devices_indices_map index to ((start, stop), ...) pairs."""
    return tuple(sl.indices(dim)[:2] for sl, dim in zip(idx, shape))


def _shard_entries(arr):
    """``[(index_pairs, owner_device_or_None)]`` for one leaf, sorted by index.

    Every distinct shard index is owned by exactly one device — the lowest
    device id holding it — so each byte of the logical tensor is written once,
    by one process, no matter how replicated the sharding is. Deterministic
    from the (global) sharding alone: every process computes the same plan
    without communicating. ``None`` owner = host value, chief-owned."""
    if not isinstance(arr, jax.Array):
        a = np.asarray(arr)
        return [(tuple((0, d) for d in a.shape), None)]
    shape = arr.shape
    owners: Dict[tuple, Any] = {}
    for dev, idx in arr.sharding.devices_indices_map(shape).items():
        key = _norm_index(idx, shape)
        if key not in owners or dev.id < owners[key].id:
            owners[key] = dev
    return sorted(owners.items())


def _encode_for_npz(data: np.ndarray):
    """npz-safe encoding: custom float dtypes (bfloat16, float8_*) are stored
    as same-width uints; the manifest records the true dtype for decode."""
    dtype = str(data.dtype)
    if data.dtype.kind not in "biufc":  # ml_dtypes customs report kind 'V'/'f'?
        data = data.view({1: np.uint8, 2: np.uint16, 4: np.uint32,
                          8: np.uint64}[data.dtype.itemsize])
    return data, dtype


def _np_dtype(name: str):
    from autodist_tpu.parallel.wire import dtype_from_name
    return dtype_from_name(name)


def _decode_from_npz(data: np.ndarray, dtype: str) -> np.ndarray:
    want = _np_dtype(dtype)
    return data if data.dtype == want else data.view(want)


def _coord_client():
    """The jax.distributed coordination-service client (None when
    jax.distributed was never initialized). Its host-side barriers are the
    right save-path synchronization: no device collectives (cannot interleave
    with training programs), and the service dies with the run — a crashed
    save can never leave a stale barrier for a restarted run, unlike
    filesystem tokens. Deliberately NO blanket except: only multi-process
    programs reach this, where the module must exist — if a jax upgrade moves
    the private API, the true ImportError/AttributeError surfaces here
    instead of a misleading 'call jax.distributed.initialize' error."""
    from jax._src import distributed
    return distributed.global_state.client




def _nest(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Rebuild a nested dict from '/'-joined names (inverse of _flatten_leaves for
    dict-based pytrees, which is what flax params are)."""
    root: Dict[str, Any] = {}
    for name, value in flat.items():
        parts = name.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


class Saver:
    """Save/restore train state or bare params, strategy-independently."""

    def __init__(self, max_to_keep: int = 5):
        self._max_to_keep = max_to_keep
        self._kept: List[str] = []
        self._rotation_loaded = False
        self._save_seq = 0           # per-instance save counter (barrier tokens)
        self._pending: Optional[threading.Thread] = None
        self._pending_error: Optional[BaseException] = None

    # ------------------------------------------------------------------- save
    def save(self, state_or_params: PyTree, save_path: str,
             global_step: Optional[int] = None, plan=None, runner=None,
             sharded: Optional[bool] = None, async_write: bool = False,
             barrier_timeout: float = 600.0) -> str:
        """Write a checkpoint. Accepts a TrainState (params + opt state + step) or a
        bare params pytree. Returns the checkpoint prefix.

        A TrainState carries its runner's plan, so padded (uneven-partition)
        storage is automatically sliced back to original logical shapes — the
        checkpoint stays strategy-independent (the reference's SaveSliceInfo
        reassembly invariant). ``runner``/``plan`` override that for bare params
        trees that came from a padded runner.

        In a multi-process program this is a COLLECTIVE: every process must
        call it at the same step. Each process writes the shards it owns; the
        chief (process 0) publishes the manifest and manages rotation. With
        one process the classic single-file format is written (``sharded=True``
        forces the sharded format anywhere).

        ``async_write=True`` snapshots device state synchronously, then runs
        all file IO on a background thread (double-buffered: a new save first
        joins the previous write). Call :meth:`wait` before reading the files
        back or exiting."""
        from autodist_tpu.runner import TrainState

        self.wait()  # double-buffer: previous async write completes (or raises)
        if plan is None and runner is not None:
            plan = runner.plan
        if plan is None and isinstance(state_or_params, TrainState):
            plan = state_or_params.plan
        unpad = plan.unpad_params if plan is not None else (lambda t: t)
        flat: Dict[str, Any] = {}
        if isinstance(state_or_params, TrainState):
            flat.update(_flatten_leaves(unpad(state_or_params.params)))
            flat.update({_OPT_PREFIX + k: v for k, v in
                         _flatten_leaves(unpad(state_or_params.opt_state)).items()})
            flat.update({_EF_PREFIX + k: v for k, v in
                         _flatten_ef_state(state_or_params.ef_state).items()})
            step = int(np.asarray(jax.device_get(state_or_params.step)))
        else:
            flat.update(_flatten_leaves(unpad(state_or_params)))
            step = 0
        # An explicit global_step overrides the state's counter for BOTH the file
        # name and the stored step, so they can never disagree.
        if global_step is not None:
            step = global_step
        prefix = f"{save_path}-{step}"
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)

        if sharded is None:
            # Sharded whenever the state cannot be assembled on one host:
            # another process holds shards (process_count > 1) — which is also
            # exactly when device_get on a leaf would raise.
            sharded = jax.process_count() > 1
        if sharded:
            return self._save_sharded(flat, save_path, prefix, step,
                                      async_write, barrier_timeout)

        # Single-file path: snapshot to host (sync), write (maybe async).
        host = {k: np.asarray(jax.device_get(v)) for k, v in flat.items()}
        host[_STEP_KEY] = np.asarray(step)
        self._run_write(async_write, self._write_single_file,
                        host, save_path, prefix, step)
        return prefix

    def wait(self):
        """Join an in-flight async write; re-raises its failure if it died."""
        t = self._pending
        if t is not None:
            t.join()
            self._pending = None
        err, self._pending_error = self._pending_error, None
        if err is not None:
            raise RuntimeError("async checkpoint write failed") from err

    def _run_write(self, async_write: bool, fn, *args):
        if not async_write:
            fn(*args)
            return

        def run():
            try:
                fn(*args)
            except BaseException as e:  # surfaced by the next wait()/save()
                self._pending_error = e
                logging.error("async checkpoint write failed: %s", e)

        self._pending = threading.Thread(target=run, daemon=True,
                                         name="autodist-ckpt-write")
        self._pending.start()

    def _write_single_file(self, host: Dict[str, np.ndarray], save_path: str,
                           prefix: str, step: int):
        tmp = prefix + ".npz.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **host)
        os.replace(tmp, prefix + ".npz")  # atomic publish

        manifest = {
            "step": step,
            "params": {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                       for k, v in host.items() if not k.startswith("__")},
        }
        tmp = prefix + ".json.tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        os.replace(tmp, prefix + ".json")

        self._load_rotation_state(save_path)  # adopt pre-restart checkpoints
        self._rotate(prefix)
        self._update_state_file(save_path, prefix)  # after rotation: lists live files
        logging.info("Saved checkpoint %s (step %d, %d tensors)",
                     prefix, step, len(host))

    def _save_sharded(self, flat: Dict[str, Any], save_path: str, prefix: str,
                      step: int, async_write: bool, barrier_timeout: float) -> str:
        """Sharded save: plan ownership (deterministic, communication-free),
        snapshot owned shards to host, then write + filesystem barrier +
        chief-published manifest (possibly on a background thread)."""
        pidx, pcount = jax.process_index(), jax.process_count()
        tensors: Dict[str, Any] = {}
        own: Dict[str, np.ndarray] = {}
        writers = set()
        for name, arr in flat.items():
            entries = []
            local = {}
            if isinstance(arr, jax.Array):
                local = {_norm_index(s.index, arr.shape): s
                         for s in arr.addressable_shards}
            for j, (idx, dev) in enumerate(_shard_entries(arr)):
                owner = 0 if dev is None else dev.process_index
                writers.add(owner)
                key = f"{name}#{j}"
                entries.append({"key": key, "file": owner,
                                "index": [[int(a), int(b)] for a, b in idx]})
                if owner == pidx:
                    data = (np.asarray(local[idx].data) if dev is not None
                            else np.asarray(arr))
                    own[key] = _encode_for_npz(data)[0]
            leaf_dtype = (str(arr.dtype) if hasattr(arr, "dtype")
                          else str(np.asarray(arr).dtype))
            leaf_shape = (list(arr.shape) if hasattr(arr, "shape")
                          else list(np.asarray(arr).shape))
            tensors[name] = {"shape": [int(d) for d in leaf_shape],
                             "dtype": leaf_dtype, "shards": entries}

        seq = self._save_seq
        self._save_seq += 1
        if pcount > 1 and _coord_client() is None:
            # No safe ordering exists without communication: any
            # filesystem-token scheme can be satisfied by artifacts a crashed
            # earlier run left at the same step, publishing a manifest over
            # stale shard data. Multi-process JAX always initializes the
            # coordination service, so refusing loudly beats silently risking
            # a corrupt checkpoint.
            raise RuntimeError(
                "Sharded multi-process save requires the jax.distributed "
                "coordination service (jax.distributed.initialize), which "
                "orders shard writes against the manifest publish")
        base = os.path.basename(prefix)
        files = {str(p): f"{base}.shard{p:05d}-of-{pcount:05d}.npz"
                 for p in sorted(writers)}
        manifest = {"format": "sharded", "step": step, "process_count": pcount,
                    "files": files, "tensors": tensors}
        self._run_write(async_write, self._write_sharded_files, own, manifest,
                        save_path, prefix, step, pidx, sorted(writers), seq,
                        barrier_timeout)
        return prefix

    def _write_sharded_files(self, own, manifest, save_path, prefix, step,
                             pidx, writers, seq, barrier_timeout):
        dirname = os.path.dirname(prefix) or "."
        pcount = manifest["process_count"]
        client = _coord_client() if pcount > 1 else None
        tag = f"adckpt:{os.path.basename(prefix)}:s{seq}"
        if pidx in writers:
            path = os.path.join(dirname, manifest["files"][str(pidx)])
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **own)
            os.replace(tmp, path)
        # Barrier 1: every writer's shard file has landed before the manifest
        # publishes, so a manifest on disk implies a complete checkpoint.
        if client is not None:
            client.wait_at_barrier(tag + ":written",
                                   timeout_in_ms=int(barrier_timeout * 1000))
        if pidx == 0:
            tmp = prefix + ".json.tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
            os.replace(tmp, prefix + ".json")
            self._load_rotation_state(save_path)
            self._rotate(prefix)
            self._update_state_file(save_path, prefix)
            logging.info(
                "Saved sharded checkpoint %s (step %d, %d tensors, %d writer "
                "processes)", prefix, step, len(manifest["tensors"]),
                len(writers))
        # Barrier 2: peers return only once the manifest exists, so a save()
        # that returned implies a restorable checkpoint everywhere.
        if client is not None:
            client.wait_at_barrier(tag + ":published",
                                   timeout_in_ms=int(barrier_timeout * 1000))

    def _load_rotation_state(self, save_path: str):
        """Seed the rotation list from the files on disk so a restarted trainer
        keeps rotating checkpoints written before the restart. Scanning
        ``<save_path>-<step>.npz`` (instead of trusting the directory's shared
        ``checkpoint`` state file) keeps rotation per *name*: two models
        checkpointing into one directory under different names never adopt —
        or delete — each other's files.

        When the state file records a rotation list for THIS name, only files in
        it are adopted: a ``<name>-<step>.npz`` the user copied aside / renamed
        into the directory to preserve beyond ``max_to_keep`` was never
        rotation-managed and must not be rotate-deleted after a restart."""
        if self._rotation_loaded:
            return
        self._rotation_loaded = True
        on_disk = [prefix for _, prefix in _scan_checkpoints(save_path)]
        _, recorded, name_pat = _read_recorded(save_path)
        ours_recorded = {p for p in recorded if name_pat.fullmatch(p)}
        if ours_recorded:
            # A previous run of this name left its rotation list: honor it.
            on_disk = [p for p in on_disk if p in ours_recorded]
        # else: no state for this name (fresh dir, deleted state file, or a state
        # file written by another name sharing the directory) — adopt the scan.
        for prefix in on_disk:
            if prefix not in self._kept:
                self._kept.append(prefix)

    def _update_state_file(self, save_path: str, prefix: str):
        """Rewrite the shared ``checkpoint`` state file, merging per name: only
        THIS name's entries are replaced by our rotation list. Two models
        checkpointing into one directory keep independent rotation records —
        the other name's entries survive, so its restarted Saver adopts its own
        recorded list instead of falling back to a full scan (which could
        rotate-delete a user-preserved ``<name>-<step>.npz``)."""
        state_path, recorded, name_pat = _read_recorded(save_path)
        others = [p for p in recorded
                  if not name_pat.fullmatch(p) and p not in self._kept]
        with open(state_path, "w") as f:
            json.dump({"latest": prefix, "all": others + list(self._kept)}, f)

    def _rotate(self, prefix: str):
        if prefix in self._kept:  # re-saving a step (e.g. checkpoint-on-resume)
            self._kept.remove(prefix)
        self._kept.append(prefix)
        while len(self._kept) > self._max_to_keep:
            victim = self._kept.pop(0)
            # ".npz"/".json" cover the single-file format; the glob sweeps a
            # sharded checkpoint's per-process files.
            doomed = {victim + ".npz", victim + ".json"}
            doomed.update(glob.glob(glob.escape(victim) + ".shard*-of-*.npz"))
            for path in doomed:
                try:
                    os.remove(path)
                except OSError:
                    pass

    # ---------------------------------------------------------------- restore
    @staticmethod
    def latest_checkpoint(directory: str, name: Optional[str] = None) -> Optional[str]:
        """Most recent checkpoint prefix in ``directory``.

        With ``name``, only checkpoints saved as ``<name>-<step>`` count — the
        directory-level ``checkpoint`` state file records whichever save ran
        last, so a directory shared by multiple names needs the filter."""
        state_path = os.path.join(directory, _STATE_FILE)
        latest = None
        if os.path.exists(state_path):
            with open(state_path) as f:
                latest = json.load(f).get("latest")
        if name is None:
            return latest
        # Exact-name match only: startswith would let "gen-ema-50" satisfy
        # name="gen" and resume the wrong model's weights.
        if latest and re.fullmatch(re.escape(name) + r"-\d+",
                                   os.path.basename(latest)) \
                and checkpoint_exists(latest):
            return latest
        # The state file points at another name's save: scan for this name's.
        found = _scan_checkpoints(os.path.join(directory, name))
        return found[-1][1] if found else None

    @staticmethod
    def _load_flat(prefix: str):
        """``(flat {name: host ndarray}, step)`` for either checkpoint format.

        Sharded checkpoints are merged on restore: full logical arrays are
        assembled from the per-process shard files per the manifest, so a
        checkpoint written by any process count restores onto any other
        (cross-topology restore — the reference restored partitioned
        checkpoints into differently-distributed runs the same way)."""
        if os.path.exists(prefix + ".npz"):
            flat = dict(np.load(prefix + ".npz"))
            step = int(flat.pop(_STEP_KEY, np.asarray(0)))
            return flat, step
        try:
            with open(prefix + ".json") as f:
                manifest = json.load(f)
        except OSError:
            raise FileNotFoundError(
                f"No checkpoint at {prefix!r} (neither {prefix}.npz nor a "
                f"sharded manifest {prefix}.json exists)") from None
        if manifest.get("format") != "sharded":
            raise FileNotFoundError(
                f"{prefix}.json is not a sharded-checkpoint manifest and "
                f"{prefix}.npz does not exist")
        dirname = os.path.dirname(prefix) or "."
        npzs: Dict[str, Any] = {}
        flat = {}
        for name, t in manifest["tensors"].items():
            out = np.empty([int(d) for d in t["shape"]], _np_dtype(t["dtype"]))
            for sh in t["shards"]:
                fname = manifest["files"][str(sh["file"])]
                z = npzs.get(fname)
                if z is None:
                    z = npzs[fname] = np.load(os.path.join(dirname, fname))
                data = _decode_from_npz(z[sh["key"]], t["dtype"])
                if out.ndim == 0:
                    out[()] = data.reshape(())
                else:
                    out[tuple(slice(a, b) for a, b in sh["index"])] = data
            flat[name] = out
        return flat, int(manifest["step"])

    def restore_params(self, prefix: str) -> Dict[str, Any]:
        """Load the parameter tree as a nested host-numpy dict (original names)."""
        flat, _ = self._load_flat(prefix)
        params = {k: v for k, v in flat.items() if not k.startswith("__")}
        return _nest(params)

    def restore(self, prefix: str, runner=None, params_template: PyTree = None):
        """Restore a checkpoint (either format).

        With ``runner``: returns a fully-placed TrainState on the runner's mesh
        (params + optimizer state + step), resharded per the runner's plan — this is
        the cross-strategy restore path. In a multi-process program every process
        calls this; each reads the shared-filesystem checkpoint and places its own
        devices' shards.
        With only ``params_template``: returns a params pytree matching the
        template's structure (for single-device / different-framework use).
        """
        flat, step = self._load_flat(prefix)
        params_flat = {k: v for k, v in flat.items()
                       if not k.startswith("__")}
        opt_flat = {k[len(_OPT_PREFIX):]: v for k, v in flat.items()
                    if k.startswith(_OPT_PREFIX)}
        ef_flat = {k[len(_EF_PREFIX):]: v for k, v in flat.items()
                   if k.startswith(_EF_PREFIX)}

        if runner is None:
            if params_template is None:
                return _nest(params_flat)
            return _fill_template(params_template, params_flat)

        # Rebuild state through the runner: init gives correctly-structured,
        # correctly-sharded state; we then overwrite leaves from the checkpoint.
        template_params = _fill_template_like_names(runner, params_flat)
        state = runner.init(template_params)
        if opt_flat:
            # Checkpoints hold logical shapes; the live opt state may be padded
            # (uneven partitioning) — fill at logical shapes, re-pad for storage.
            opt_template = runner.plan.unpad_params(state.opt_state)
            opt_state = runner.plan.pad_params(
                _fill_template(opt_template, opt_flat, strict=False))
            o_sh = runner.plan.opt_sharding_tree(runner.mesh, opt_state)
            opt_state = _place_tree(opt_state, o_sh)
        else:
            opt_state = state.opt_state
        if ef_flat:
            ef_state = _fill_template(state.ef_state, ef_flat, strict=False,
                                      on_mismatch="reinit")
            ef_state = _place_tree(
                ef_state,
                jax.tree_util.tree_map(lambda l: l.sharding, state.ef_state))
        else:
            ef_state = state.ef_state
        from autodist_tpu.runner import TrainState
        return TrainState(step=np.asarray(step, np.int32), params=state.params,
                          opt_state=opt_state, ef_state=ef_state, plan=runner.plan)


def _place_tree(tree: PyTree, shardings: PyTree) -> PyTree:
    """Place host leaves with their shardings, multiprocess-safe.

    ``jax.device_put`` onto a non-fully-addressable sharding runs a
    cross-process value check that heterogeneous clusters violate (see
    ``runner.place_host_value``); leaves already resident with the right
    sharding pass through untouched (template leaves the checkpoint did not
    override, which may themselves be non-addressable)."""
    from autodist_tpu.runner import place_host_value

    def put(leaf, sh):
        if isinstance(leaf, jax.Array) and leaf.sharding == sh:
            return leaf
        return place_host_value(leaf, sh)

    return jax.tree_util.tree_map(put, tree, shardings)


def _flatten_ef_state(ef_state: PyTree) -> Dict[str, np.ndarray]:
    """Flatten compressor state, dropping per-replica residuals by leaf identity.

    Per-replica [dp, ...] error-feedback residuals are transient worker-local
    state (the reference kept them in-memory per worker, compressor.py:120-143):
    checkpointing them would cost dp x parameter size and they cannot restore onto
    a different topology anyway. Shape-stable compressor state (PowerSGD's Q) is
    checkpointed. Residuals are identified as the ``error`` *attribute* of the
    EFState/PowerSGDState dataclasses (a GetAttrKey in the tree path) — a model
    parameter that happens to be named 'error' (a DictKey) is saved normally."""
    from autodist_tpu.model_spec import _path_name
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(ef_state)[0]:
        last = path[-1] if path else None
        if isinstance(last, jax.tree_util.GetAttrKey) and last.name == "error":
            continue
        out[_path_name(path)] = leaf  # materialized (or shard-planned) later
    return out


def _fill_template(template: PyTree, flat: Dict[str, np.ndarray], strict: bool = True,
                   on_mismatch: str = "raise"):
    """Replace template leaves by name; leaves missing from the checkpoint are kept
    (strict=False) or are an error (strict=True). A shape mismatch raises
    (``on_mismatch='raise'``) or keeps the template leaf with a warning
    (``on_mismatch='reinit'`` — used for compressor state whose shapes depend on the
    data-parallel topology)."""
    from autodist_tpu.model_spec import _path_name

    def fill(path, leaf):
        name = _path_name(path)
        if name in flat:
            value = flat[name]
            if tuple(value.shape) != tuple(getattr(leaf, "shape", value.shape)):
                if on_mismatch == "reinit":
                    logging.warning(
                        "Reinitializing %s: saved shape %s does not match current %s "
                        "(topology changed)", name, tuple(value.shape), tuple(leaf.shape))
                    return leaf
                raise ValueError(f"Checkpoint shape mismatch for {name}: "
                                 f"{value.shape} vs {leaf.shape}")
            return value
        if strict:
            raise KeyError(f"Checkpoint missing parameter {name!r}")
        return leaf

    return jax.tree_util.tree_map_with_path(fill, template)


def _fill_template_like_names(runner, params_flat):
    """Build a params pytree for runner.init from checkpoint names using the
    runner's recorded tree structure."""
    spec = runner._model_spec
    leaves = []
    for name in spec.names:
        if name not in params_flat:
            raise KeyError(f"Checkpoint missing parameter {name!r}")
        leaves.append(params_flat[name])
    return spec.unflatten(leaves)
