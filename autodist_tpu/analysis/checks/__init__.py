"""Check modules; importing this package populates the registry.

Each module registers with :func:`autodist_tpu.analysis.core.register`
(per-module checks) or :func:`~autodist_tpu.analysis.core.register_program`
(whole-program checks over the
:class:`~autodist_tpu.analysis.program.ProgramIndex`). Check ownership:

- concurrency:      GL001 lock-held-across-dispatch (program),
                    GL002 lock-order (program), GL005 unbounded-blocking
- donation:         GL003 use-after-donate
- tracer:           GL004 tracer leak
- wire_protocol:    GL006 opcode/tag exhaustiveness + frame-version order
- envflags:         GL007 AUTODIST_* flag registry
- testlayout:       GL008 tier-1 test-window conventions
- metrics_registry: GL009 metric/event-name registry (program)
- resources:        GL010 resource-close discipline (program)
- wire_idempotency: GL011 wire-retry idempotency contract (program)
- races:            GL012 guarded-field consistency (program)
"""

from autodist_tpu.analysis.checks import (  # noqa: F401
    concurrency, donation, envflags, metrics_registry, races, resources,
    testlayout, tracer, wire_idempotency, wire_protocol)
