"""Pipeline parallelism over the mesh ``pipe`` axis (GPipe and 1F1B schedules).

Beyond reference parity: the reference explicitly scoped pipeline parallelism out
(``docs/design/architecture.rst:49-51``, SURVEY.md §2.2). The TPU-native design is
the collective-permute formulation: stage parameters are sharded ``P("pipe", ...)``
on their leading stage dimension, and inside a ``jax.shard_map`` manual region over
the ``pipe`` axis each device runs its stage on a stream of microbatches, handing
activations to the next stage with ``lax.ppermute``.

Three schedules:

- **GPipe** (:func:`pipelined`): a single forward ``lax.scan`` of
  ``num_microbatches + n_stages - 1`` ticks; reverse-mode autodiff through the
  scan yields the backward pipeline automatically. Simple, but autodiff stores
  every tick's residuals, so live activation memory grows with
  ``num_microbatches``.
- **1F1B** (:func:`pipelined_value_and_grad`): each tick runs one forward AND
  one backward slot per stage; a microbatch's backward starts as soon as its
  activations return from downstream, so at most ``2*n_stages - 1`` microbatch
  inputs are live per stage — activation memory is O(n_stages), independent of
  the microbatch count. Backward recomputes the stage forward from its saved
  INPUT (``jax.vjp`` inside the tick), the standard remat trade: one extra
  forward per microbatch buys the O(n_stages) residency. The loss (tail) runs
  inside the schedule at the last stage, which is what makes the interleaving
  possible; total ticks = ``num_microbatches + 2*(n_stages - 1)`` versus
  GPipe's ``2*(num_microbatches + n_stages - 1)``.
- **Interleaved 1F1B** (:func:`interleaved_value_and_grad`): each device holds
  ``v`` model CHUNKS (virtual stages) instead of one fat stage — chunk ``c``
  of ``V = S*v`` lives on device ``c mod S`` — so pipeline ticks are
  thin-chunk-sized. Fill/drain overhead drops from ``2(S-1)`` fat ticks
  (``= 2v(S-1)`` thin-tick equivalents of compute) to ``(v+1)S - 2`` thin
  ticks — a ``~(v+1)/2v`` bubble ratio, approaching half for large ``v`` —
  at the cost of a deeper input ring (``O(v*S)`` saved microbatch inputs
  per device vs ``O(S)``). The schedule is closed-form:
  device ``r``'s ``i``-th forward slot processes microbatch group ``i //
  (S*v)``, chunk ``(i % (S*v)) // S``, group position ``i % S``; backwards
  mirror it in reverse chunk order, offset by ``delay(r) = 2(S-1) + (v-1)S -
  r``; every activation hop then lands exactly one ``ppermute`` (with ring
  wrap) ahead of its consumer.

All are written for the *partial-manual* shard_map mode (``axis_names=
{"pipe"}``): every other mesh axis stays under automatic SPMD partitioning, so
pipeline composes with data parallelism (batch stays sharded on ``data``) and the
other strategies.
"""

from typing import Callable

import jax
import jax.numpy as jnp

from autodist_tpu import const

PyTree = object


def pipeline_apply(stage_fn: Callable, stage_params: PyTree, x_mb: jax.Array,
                   axis: str = const.MESH_AXIS_PIPE) -> jax.Array:
    """GPipe loop body — must run inside a shard_map manual over ``axis``.

    stage_fn(stage_params, x) -> y applies one pipeline stage to one microbatch
    (``stage_params`` is this device's shard: leading stage dim of size 1).
    x_mb: [num_microbatches, mb_batch, ...] activations entering stage 0,
    replicated along ``axis`` (only rank 0 reads them; the transpose of that read
    routes the input gradient back correctly). Returns the last stage's outputs,
    [num_microbatches, mb_batch, ...], replicated along ``axis``.
    """
    n_stages = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    n_mb = x_mb.shape[0]

    if n_stages == 1:
        # Degenerate single-stage pipeline: no schedule needed.
        def apply_one(carry, x):
            return carry, stage_fn(stage_params, x)
        _, out = jax.lax.scan(apply_one, 0, x_mb)
        return out

    shift_pairs = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, outputs = carry
        mb = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False)
        x = jnp.where(rank == 0, mb, state)
        y = stage_fn(stage_params, x)
        # The last stage starts emitting results at tick n_stages-1.
        take = (t >= n_stages - 1) & (rank == n_stages - 1)
        idx = jnp.clip(t - (n_stages - 1), 0, n_mb - 1)
        prev = jax.lax.dynamic_index_in_dim(outputs, idx, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(take, y, prev), idx, 0)
        nxt = jax.lax.ppermute(y, axis, shift_pairs)
        return (nxt, outputs), None

    init = (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb))
    (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(n_mb + n_stages - 1))
    # Broadcast the last stage's results to every pipe rank so downstream
    # (replicated) computation — the LM head, the loss — sees them everywhere.
    mask = (rank == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * mask, axis)


def onef_oneb_apply(stage_fn: Callable, tail_fn: Callable, stage_params: PyTree,
                    tail_params: PyTree, x_mb: jax.Array, targets_mb: PyTree,
                    axis: str = const.MESH_AXIS_PIPE):
    """1F1B loop body — must run inside a shard_map manual over ``axis``.

    ``stage_fn(stage_params, x) -> y`` is one stage on one microbatch;
    ``tail_fn(tail_params, y, target) -> scalar`` is the post-pipeline head +
    loss for one microbatch (run at the LAST stage, inside the schedule — the
    placement that lets a microbatch's backward start while later microbatches
    are still filling). Returns ``(mean_loss, stage_grads, tail_grads,
    x_grads)``; ``x_grads`` is [M, ...] (d loss / d x_mb, for callers with
    trainable pre-pipeline computation).

    Schedule (S stages, M microbatches, tick t): stage r runs the forward of
    microbatch ``t - r`` and the backward of microbatch ``t - (2S - 2 - r)``
    (each when in [0, M)). Forward activations hop r -> r+1, backward input
    grads hop r -> r-1, one ppermute each per tick. A microbatch's input is
    held from its forward to its backward — at most ``2(S-1-r) + 1`` live per
    stage, hence the O(n_stages) activation footprint.
    """
    n_stages = jax.lax.psum(1, axis)
    n_mb = x_mb.shape[0]

    def mb_at(tree, k):
        return jax.tree_util.tree_map(
            lambda l: jax.lax.dynamic_index_in_dim(l, k, 0, keepdims=False),
            tree)

    if n_stages == 1:
        # Degenerate: plain per-microbatch value_and_grad accumulation.
        def one(carry, k):
            gs, gt, gx, acc = carry
            def full(sp, tp, x):
                return tail_fn(tp, stage_fn(sp, x), mb_at(targets_mb, k))
            (l, (dgs, dgt, dgx)) = jax.value_and_grad(full, argnums=(0, 1, 2))(
                stage_params, tail_params, x_mb[k])
            gs = jax.tree_util.tree_map(jnp.add, gs, dgs)
            gt = jax.tree_util.tree_map(jnp.add, gt, dgt)
            gx = jax.lax.dynamic_update_index_in_dim(gx, dgx, k, 0)
            return (gs, gt, gx, acc + l), None
        zeros_s = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
        zeros_t = jax.tree_util.tree_map(jnp.zeros_like, tail_params)
        (gs, gt, gx, acc), _ = jax.lax.scan(
            one, (zeros_s, zeros_t, jnp.zeros_like(x_mb), jnp.zeros(())),
            jnp.arange(n_mb))
        scale = 1.0 / n_mb
        return (acc * scale,
                jax.tree_util.tree_map(lambda g: g * scale, gs),
                jax.tree_util.tree_map(lambda g: g * scale, gt),
                gx * scale)

    # General case: exactly the interleaved schedule with one chunk per
    # device — the slot arithmetic, ring sizing, delay offset, masks, and
    # ownership psums all reduce to the plain-1F1B formulas at n_chunks=1
    # (pinned by tests), so ONE tick body serves both schedules.
    return interleaved_onef_oneb_apply(stage_fn, tail_fn, stage_params,
                                       tail_params, x_mb, targets_mb,
                                       n_chunks=1, axis=axis)


def interleaved_onef_oneb_apply(stage_fn: Callable, tail_fn: Callable,
                                stage_params: PyTree, tail_params: PyTree,
                                x_mb: jax.Array, targets_mb: PyTree,
                                n_chunks: int,
                                axis: str = const.MESH_AXIS_PIPE):
    """Interleaved-1F1B loop body — must run inside a shard_map manual over
    ``axis``. ``stage_params`` is this device's LOCAL chunk block: leading dim
    ``n_chunks`` (= v), local index ``j`` holding VIRTUAL stage ``j*S + r``
    (device-major layout; :func:`interleave_chunk_layout` converts from
    virtual-stage order). Returns ``(mean_loss, stage_grads, tail_grads,
    x_grads)`` with ``stage_grads`` in the same local layout.

    Per thin-tick, a device runs ONE chunk forward and ONE chunk backward
    (masked in fill/drain). Slot -> (group, chunk, position) index arithmetic
    and the ``delay(r)`` backward offset are chosen so every forward hop
    ``c -> c+1`` and backward hop ``c -> c-1`` — including the ring wraps
    ``S-1 -> 0`` (forward, entering the next chunk group) and ``0 -> S-1``
    (backward) — is produced exactly one tick before its consumer reads it
    (see the module docstring for the derivation)."""
    n_stages = jax.lax.psum(1, axis)
    rank = jax.lax.axis_index(axis)
    v = n_chunks
    n_mb = x_mb.shape[0]
    if v > 1 and n_mb % n_stages:
        # The slot decomposition advances microbatches in groups of S; a
        # ragged final group would silently process some (mb, chunk) pairs
        # twice and skip others — finite, plausible, WRONG gradients.
        raise ValueError(
            f"interleaved 1F1B needs num_microbatches divisible by n_stages "
            f"({n_mb} % {n_stages} != 0); pad the microbatch count")
    sv = n_stages * v
    total_slots = n_mb * v              # forward (= backward) slots per device

    def mb_at(tree, k):
        return jax.tree_util.tree_map(
            lambda l: jax.lax.dynamic_index_in_dim(l, k, 0, keepdims=False),
            tree)

    def chunk_at(tree, j):
        # Keep the size-1 leading dim: stage_fn's contract (shared with plain
        # 1F1B) is a per-device block whose leading stage dim is 1.
        return jax.tree_util.tree_map(
            lambda l: jax.lax.dynamic_slice_in_dim(l, j, 1, axis=0), tree)

    # Max saved-input lifetime: T_b - T_f at r=0, j=0 (see docstring), +1.
    ring_size = 2 * (n_stages - 1) + 2 * (v - 1) * n_stages + 1
    delay = 2 * (n_stages - 1) + (v - 1) * n_stages - rank
    # Ring wraps (forward S-1 -> 0, backward 0 -> S-1) only exist to carry a
    # microbatch across chunk-group transitions; at v=1 there are none and
    # the wrap payloads would be pure dead inter-device traffic every tick.
    if v > 1:
        fwd_pairs = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_pairs = [((i + 1) % n_stages, i) for i in range(n_stages)]
    else:
        fwd_pairs = [(i, i + 1) for i in range(n_stages - 1)]
        bwd_pairs = [(i + 1, i) for i in range(n_stages - 1)]

    def decompose_f(idx):
        g, rem = idx // sv, idx % sv
        return g * n_stages + rem % n_stages, rem // n_stages   # (mb, chunk)

    def decompose_b(idx):
        g, rem = idx // sv, idx % sv
        return g * n_stages + rem % n_stages, v - 1 - rem // n_stages

    def tick(carry, t):
        a_recv, g_recv, ring, gs, gt, gx_buf, loss_acc = carry

        # ---- F slot ------------------------------------------------------
        f_idx = t - rank
        f_valid = (f_idx >= 0) & (f_idx < total_slots)
        f_idx_c = jnp.clip(f_idx, 0, total_slots - 1)
        m_f, j_f = decompose_f(f_idx_c)
        c_f = j_f * n_stages + rank                      # virtual stage id
        x_in = jnp.where(c_f == 0,
                         jax.lax.dynamic_index_in_dim(
                             x_mb, jnp.clip(m_f, 0, n_mb - 1), 0,
                             keepdims=False),
                         a_recv)
        y = stage_fn(chunk_at(stage_params, j_f), x_in)
        slot_f = jnp.mod(f_idx_c, ring_size)
        kept = jax.lax.dynamic_index_in_dim(ring, slot_f, 0, keepdims=False)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(f_valid, x_in, kept), slot_f, 0)

        # ---- B slot ------------------------------------------------------
        b_idx = t - delay
        b_valid = (b_idx >= 0) & (b_idx < total_slots)
        b_idx_c = jnp.clip(b_idx, 0, total_slots - 1)
        m_b, j_b = decompose_b(b_idx_c)
        c_b = j_b * n_stages + rank
        # The saved input of (m_b, chunk j_b) went into the ring under ITS
        # forward slot index.
        f_of_b = (m_b // n_stages) * sv + j_b * n_stages + m_b % n_stages
        x_saved = jax.lax.dynamic_index_in_dim(
            ring, jnp.mod(f_of_b, ring_size), 0, keepdims=False)
        params_b = chunk_at(stage_params, j_b)
        y_b, vjp = jax.vjp(stage_fn, params_b, x_saved)
        tgt = mb_at(targets_mb, jnp.clip(m_b, 0, n_mb - 1))
        is_last = c_b == sv - 1                          # loss-owning stage

        # The tail (head matmul + loss + its VJP — the vocab-sized work for
        # LM models) contributes ONLY at valid last-stage slots; lax.cond
        # skips it elsewhere instead of computing-then-masking — without the
        # gate every rank/chunk/tick would pay it, and interleaving
        # multiplies the tick count by v.
        def run_tail(args):
            tp, y, t_ = args
            return jax.value_and_grad(tail_fn, argnums=(0, 1))(tp, y, t_)

        def skip_tail(args):
            # Zeros in run_tail's exact output structure/dtypes (eval_shape:
            # no compute traced) — cond branches must match precisely.
            shapes = jax.eval_shape(run_tail, args)
            return jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes)

        loss_k, (d_tail, d_y) = jax.lax.cond(
            b_valid & is_last, run_tail, skip_tail, (tail_params, y_b, tgt))
        g_y = jnp.where(is_last, d_y, g_recv)
        d_stage, d_x = vjp(g_y)
        upd = b_valid

        def acc_chunk(acc, g):
            # g rides the [1, ...] leading block shape chunk_at produced.
            cur = jax.lax.dynamic_slice_in_dim(acc, j_b, 1, axis=0)
            return jax.lax.dynamic_update_slice_in_dim(
                acc, cur + jnp.where(upd, g, 0), j_b, axis=0)

        gs = jax.tree_util.tree_map(acc_chunk, gs, d_stage)
        gt = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(upd & is_last, g, 0), gt, d_tail)
        loss_acc = loss_acc + jnp.where(upd & is_last, loss_k, 0.0)
        k_x = jnp.clip(m_b, 0, n_mb - 1)
        prev = jax.lax.dynamic_index_in_dim(gx_buf, k_x, 0, keepdims=False)
        gx_buf = jax.lax.dynamic_update_index_in_dim(
            gx_buf, jnp.where(upd & (c_b == 0), d_x, prev), k_x, 0)

        a_next = jax.lax.ppermute(y, axis, fwd_pairs)
        g_next = jax.lax.ppermute(d_x, axis, bwd_pairs)
        return (a_next, g_next, ring, gs, gt, gx_buf, loss_acc), None

    zeros_s = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    zeros_t = jax.tree_util.tree_map(jnp.zeros_like, tail_params)
    init = (
        jnp.zeros_like(x_mb[0]),
        jnp.zeros_like(x_mb[0]),
        jnp.zeros((ring_size,) + x_mb.shape[1:], x_mb.dtype),
        zeros_s, zeros_t,
        jnp.zeros_like(x_mb),
        jnp.zeros(()),
    )
    # Last backward: r=0, b_idx = total_slots - 1 -> tick delay(0) + that.
    n_ticks = total_slots + 2 * (n_stages - 1) + (v - 1) * n_stages
    (_, _, _, gs, gt, gx_buf, loss_acc), _ = jax.lax.scan(
        tick, init, jnp.arange(n_ticks))

    scale = 1.0 / n_mb
    last_rank = n_stages - 1                 # stage V-1 lives on device S-1
    loss = jax.lax.psum(
        loss_acc * (rank == last_rank).astype(loss_acc.dtype), axis) * scale
    gt = jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g * (rank == last_rank).astype(g.dtype), axis)
        * scale, gt)
    gx = jax.lax.psum(gx_buf * (rank == 0).astype(gx_buf.dtype), axis) * scale
    gs = jax.tree_util.tree_map(lambda g: g * scale, gs)
    return loss, gs, gt, gx


def interleave_chunk_layout(tree: PyTree, n_stages: int, n_chunks: int,
                            inverse: bool = False) -> PyTree:
    """Permute leading-dim-``V`` leaves between VIRTUAL-stage order (chunk
    ``c`` at row ``c`` — the natural model layout) and the DEVICE-MAJOR order
    :func:`interleaved_value_and_grad` shards (row ``r*v + j`` = virtual stage
    ``j*S + r``, so ``P(axis)`` on dim 0 gives device ``r`` exactly its
    chunks). Apply once at init (and ``inverse=True`` on returned grads if
    you want them back in virtual order) — NOT inside the step, where the
    cross-device gather would cost every tick."""
    import numpy as _np
    idx = _np.asarray(chunk_perm(n_stages, n_chunks, inverse=inverse))
    return jax.tree_util.tree_map(lambda l: jnp.take(l, idx, axis=0), tree)


def chunk_perm(n_stages: int, n_chunks: int, inverse: bool = False):
    """THE device-major <-> virtual row permutation (one definition, shared
    by :func:`interleave_chunk_layout` and the model-layer layout helpers):
    ``perm[row]`` = source row. Forward: device-major row ``r*v + j`` reads
    virtual row ``j*S + r``; inverse: virtual row ``j*S + r`` reads
    device-major row ``r*v + j``."""
    v, s = n_chunks, n_stages
    if inverse:
        return [(row % s) * v + row // s for row in range(s * v)]
    return [(row % v) * s + row // v for row in range(s * v)]


def interleaved_value_and_grad(stage_fn: Callable, tail_fn: Callable,
                               n_stages: int, n_chunks: int,
                               axis: str = const.MESH_AXIS_PIPE,
                               mesh=None) -> Callable:
    """Wrap :func:`interleaved_onef_oneb_apply` in the partial-manual
    shard_map.

    Returns ``f(stage_params, tail_params, x_mb, targets_mb) -> (mean_loss,
    stage_grads, tail_grads, x_grads)``. ``stage_params`` leaves carry a
    leading dim ``V = n_stages * n_chunks`` in DEVICE-MAJOR layout (use
    :func:`interleave_chunk_layout` to convert from virtual-stage order),
    sharded over ``axis``; grads come back in the same layout. ``n_chunks=1``
    is exactly the plain 1F1B schedule."""
    from jax.sharding import PartitionSpec as P

    def f(stage_params, tail_params, x_mb, targets_mb):
        m, specs = _pipe_mesh_and_specs("interleaved_value_and_grad", mesh,
                                        axis, n_stages, stage_params,
                                        stage_rows=n_stages * n_chunks)
        tail_zero = jax.tree_util.tree_map(lambda _: P(), tail_params)
        tgt_zero = jax.tree_util.tree_map(lambda _: P(), targets_mb)
        return jax.shard_map(
            lambda sp, tp, x, tg: interleaved_onef_oneb_apply(
                stage_fn, tail_fn, sp, tp, x, tg, n_chunks, axis=axis),
            mesh=m,
            in_specs=(specs, tail_zero, P(), tgt_zero),
            out_specs=(P(), specs, tail_zero, P()),
            axis_names={axis}, check_vma=False,
        )(stage_params, tail_params, x_mb, targets_mb)

    return f


def pipelined_value_and_grad(stage_fn: Callable, tail_fn: Callable,
                             n_stages: int, axis: str = const.MESH_AXIS_PIPE,
                             mesh=None) -> Callable:
    """Wrap :func:`onef_oneb_apply` (the 1F1B schedule) in the partial-manual
    shard_map.

    Returns ``f(stage_params, tail_params, x_mb, targets_mb) ->
    (mean_loss, stage_grads, tail_grads, x_grads)``. ``stage_params`` leaves
    carry a leading stage dimension of size ``n_stages`` (sharded over
    ``axis``); ``tail_params`` (head + loss parameters) are replicated;
    ``x_mb``/``targets_mb`` are [num_microbatches, mb_batch, ...]. Must run
    under ``jit``. Keep GPipe (:func:`pipelined` + autodiff) for the simple
    mode; choose 1F1B when activation memory, not schedule simplicity, is the
    constraint.
    """
    from jax.sharding import PartitionSpec as P

    def f(stage_params, tail_params, x_mb, targets_mb):
        m, specs = _pipe_mesh_and_specs("pipelined_value_and_grad", mesh,
                                        axis, n_stages, stage_params)
        tail_zero = jax.tree_util.tree_map(lambda _: P(), tail_params)
        tgt_zero = jax.tree_util.tree_map(lambda _: P(), targets_mb)
        return jax.shard_map(
            lambda sp, tp, x, tg: onef_oneb_apply(stage_fn, tail_fn, sp, tp,
                                                  x, tg, axis=axis),
            mesh=m,
            in_specs=(specs, tail_zero, P(), tgt_zero),
            out_specs=(P(), specs, tail_zero, P()),
            axis_names={axis}, check_vma=False,
        )(stage_params, tail_params, x_mb, targets_mb)

    return f


def _pipe_mesh_and_specs(fn_name: str, mesh, axis: str, n_stages: int,
                         stage_params, stage_rows: int = None):
    """Shared mesh resolution + stage-size validation + P(axis) spec build for
    the schedule wrappers. Without the size check a mismatched mesh silently
    runs only the stage groups the pipe axis covers — finite loss, most
    layers skipped. ``stage_rows`` (interleaved: S*v) validates the params'
    leading dim when it differs from the axis size."""
    from jax.sharding import PartitionSpec as P

    m = mesh if mesh is not None else _ambient_mesh()
    mesh_stages = dict(m.shape).get(axis, 1)
    if mesh_stages != n_stages:
        raise ValueError(
            f"{fn_name}(n_stages={n_stages}) needs mesh axis {axis!r} of that "
            f"size, but the mesh has {axis}={mesh_stages}; size the mesh with "
            f"the Pipeline strategy or a matching resource-spec mesh")
    rows = n_stages if stage_rows is None else stage_rows
    for path, leaf in jax.tree_util.tree_flatten_with_path(stage_params)[0]:
        shape = getattr(leaf, "shape", None)
        if shape is not None and (len(shape) == 0 or shape[0] != rows):
            raise ValueError(
                f"{fn_name}: stage_params leaves need leading dim {rows}, "
                f"got {shape} at {jax.tree_util.keystr(path)}")
    return m, jax.tree_util.tree_map(lambda _: P(axis), stage_params)


def _ambient_mesh():
    """The mesh in effect at trace time: the abstract-mesh context if set, else the
    ``with mesh:`` physical-mesh context the runner steps under."""
    abstract = jax.sharding.get_abstract_mesh()
    if abstract is not None and not abstract.empty:
        return abstract
    try:
        # No public accessor for the `with mesh:` context; degrade to the
        # explicit-mesh error if a jax upgrade moves this.
        from jax._src import mesh as mesh_lib
        physical = mesh_lib.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        physical = None
    if physical is not None and not physical.empty:
        return physical
    raise RuntimeError(
        "pipelined() needs a mesh: pass one explicitly or call inside a "
        "`with mesh:` block (DistributedRunner.run steps under one)")


def pipelined(stage_fn: Callable, n_stages: int, axis: str = const.MESH_AXIS_PIPE,
              mesh=None) -> Callable:
    """Wrap :func:`pipeline_apply` in the partial-manual shard_map.

    Returns ``f(stage_params, x_mb) -> y_mb`` where ``stage_params`` leaves carry a
    leading stage dimension of size ``n_stages`` (sharded over ``axis``) and all
    other mesh axes remain automatic. ``mesh`` defaults to the ambient mesh
    context (the runner steps inside ``with self.mesh``). Must run under ``jit``
    (partial-manual shard_map is trace-time only).
    """
    from jax.sharding import PartitionSpec as P

    def f(stage_params, x_mb):
        m, specs = _pipe_mesh_and_specs("pipelined", mesh, axis, n_stages,
                                        stage_params)
        return jax.shard_map(
            lambda p, x: pipeline_apply(stage_fn, p, x, axis=axis),
            mesh=m, in_specs=(specs, P()), out_specs=P(),
            axis_names={axis}, check_vma=False,
        )(stage_params, x_mb)

    return f
