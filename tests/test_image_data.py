"""Image pipeline: tree prep, device augmentation, disk-fed CNN training.

Parity target: reference ``examples/benchmark/imagenet.py:219-229`` (input_fn
over a real data_dir) + ``utils/imagenet_preprocessing.py`` (decode, crop,
flip, mean subtraction). Here prep decodes offline into uint8 record shards
and crop/flip/normalize run on device inside the jitted step.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.data import DataLoader, imagenet


def _write_tree(root, n_classes=3, per_class=8, seed=0):
    """A tiny JPEG tree with per-class constant-ish colors (so labels are
    learnable) and varied aspect ratios (so resize paths are exercised)."""
    from PIL import Image
    rng = np.random.RandomState(seed)
    sizes = [(48, 64), (64, 48), (56, 56), (80, 40)]
    for c in range(n_classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d)
        base = np.zeros(3)
        base[c % 3] = 200
        for i in range(per_class):
            w, h = sizes[i % len(sizes)]
            arr = np.clip(base[None, None, :] + rng.randint(-30, 30, (h, w, 3)),
                          0, 255).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img{i:03d}.jpg"),
                                      quality=92)


def test_prepare_image_shards_layout(tmp_path):
    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=3, per_class=8)
    out = str(tmp_path / "shards")
    paths = imagenet.prepare_image_shards(tree, out, record_size=32,
                                          rows_per_shard=10)
    meta = imagenet.read_meta(out)
    assert meta["record_size"] == 32 and meta["rows"] == 24
    assert meta["classes"] == ["class0", "class1", "class2"]
    imgs = np.concatenate([np.load(p) for p in paths["images"]])
    labs = np.concatenate([np.load(p) for p in paths["labels"]])
    assert imgs.shape == (24, 32, 32, 3) and imgs.dtype == np.uint8
    assert labs.shape == (24,) and labs.dtype == np.int32
    assert set(labs) == {0, 1, 2}
    # Class colors survive decode/resize/crop: the dominant channel of each
    # record matches its label (class c is bright in channel c).
    per_img_mean = imgs.astype(np.float32).mean(axis=(1, 2))
    assert (per_img_mean.argmax(axis=1) == labs).all()
    # Shuffled before sharding: the first shard is not all one class.
    assert len(set(np.load(paths["labels"][0]))) > 1


def test_augment_matches_numpy_reference():
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 16, 16, 3)).astype(np.uint8)
    crop = np.asarray([[0, 0], [3, 1], [4, 4], [2, 0]], np.int32)
    flip = np.asarray([False, True, False, True])
    out = np.asarray(imagenet.augment_images(jnp.asarray(imgs),
                                             jnp.asarray(crop),
                                             jnp.asarray(flip), 12))
    for i in range(4):
        ref = imgs[i, crop[i, 0]:crop[i, 0] + 12,
                   crop[i, 1]:crop[i, 1] + 12, :].astype(np.float32)
        if flip[i]:
            ref = ref[:, ::-1, :]
        ref = ref - np.asarray(imagenet.CHANNEL_MEANS, np.float32)
        np.testing.assert_allclose(out[i], ref, rtol=0, atol=0)


def test_batcher_train_vs_eval(tmp_path):
    tree = str(tmp_path / "tree")
    _write_tree(tree)
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=32, rows_per_shard=64)
    loader, meta = imagenet.open_image_loader(out, batch_size=6, shuffle=True,
                                              seed=1, native=False)
    train = imagenet.AugmentingBatcher(loader, image_size=24, record_size=32,
                                       train=True, seed=5)
    b = train.next()
    assert b["images"].dtype == np.uint8 and b["crop_yx"].shape == (6, 2)
    assert (b["crop_yx"] >= 0).all() and (b["crop_yx"] <= 8).all()
    # Deterministic under (loader seed, batcher seed).
    loader2, _ = imagenet.open_image_loader(out, batch_size=6, shuffle=True,
                                            seed=1, native=False)
    train2 = imagenet.AugmentingBatcher(loader2, image_size=24, record_size=32,
                                        train=True, seed=5)
    b2 = train2.next()
    for k in b:
        np.testing.assert_array_equal(b[k], b2[k])
    # Eval: fixed center crop, no flips.
    loader3, _ = imagenet.open_image_loader(out, batch_size=6, shuffle=False,
                                            native=False)
    ev = imagenet.AugmentingBatcher(loader3, image_size=24, record_size=32,
                                    train=False)
    e = ev.next()
    assert (e["crop_yx"] == 4).all() and not e["flip"].any()
    loader.close(), loader2.close(), loader3.close()

    with pytest.raises(ValueError, match="exceeds record_size"):
        imagenet.AugmentingBatcher(loader, image_size=64, record_size=32)


def test_device_dataset_cache_assembles_and_refreshes(tmp_path):
    """The HBM record pool: batches gather+augment on device and match the
    numpy reference; background refresh cycles new disk rows into the pool."""
    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=3, per_class=16)  # 48 rows
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=32, rows_per_shard=64)
    loader, meta = imagenet.open_image_loader(out, batch_size=16, shuffle=False,
                                              native=False)
    cache = imagenet.DeviceDatasetCache(
        loader, record_size=32, image_size=24, pool_rows=32,
        refresh_rows=8, refresh_interval=2, seed=7)
    assert cache.pool_rows == 32

    pool_before = np.asarray(cache._pool)
    batch = cache.next_batch(6)
    assert batch["images"].shape == (6, 24, 24, 3)
    assert batch["labels"].shape == (6,) and batch["labels"].dtype == np.int32
    # Assembly correctness: replay the same rng draws against the host pool.
    rng = np.random.Generator(np.random.PCG64(7))
    idx = rng.integers(0, 32, size=6, dtype=np.int32)
    crop = rng.integers(0, 9, size=(6, 2), dtype=np.int32)
    flip = rng.random(6) < 0.5
    expect = np.asarray(imagenet.augment_images(pool_before[idx], crop, flip, 24))
    np.testing.assert_allclose(np.asarray(batch["images"]), expect, atol=0)

    # Refresh: the loader holds 48 rows vs a 32-row pool; after several ticks
    # the pool must have absorbed rows it did not start with.
    for _ in range(12):
        cache.next_batch(6)
    pool_after = np.asarray(cache._pool)
    assert not np.array_equal(pool_before, pool_after)
    loader.close()


def test_native_loader_serves_image_rows(tmp_path):
    """The C++ gather ring on image-shaped rows (4-D uint8, ~3 KB each): one
    shuffled epoch serves every record exactly once with labels still
    row-aligned to their images — the wide-tensor case the data plane's
    generic tests (2-D float) don't shape-check. (Native and fallback RNGs
    differ by design, so the check is coverage, not order.)"""
    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=2, per_class=10)  # 20 rows
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=32, rows_per_shard=8)
    loader, _ = imagenet.open_image_loader(out, batch_size=5, shuffle=True,
                                           seed=3, native=None)
    if not loader.is_native:
        loader.close()
        pytest.skip("no C++ toolchain in this environment")
    rows = []
    for _ in range(4):  # 20 rows / batch 5 = one full epoch
        b = loader.next()
        assert b["images"].shape == (5, 32, 32, 3)
        for img, lab in zip(b["images"], b["labels"]):
            # Row alignment survives the native gather: class c is bright in
            # channel c (the prep-tree invariant).
            assert img.astype(np.float32).mean(axis=(0, 1)).argmax() == lab
            rows.append(img.tobytes())
    assert len(set(rows)) == 20  # every record exactly once per epoch
    loader.close()


def test_device_dataset_cache_no_duplicates_on_non_divisible_dataset(tmp_path):
    """48 rows at loader batch 10: only 40 are servable (drop-last), so the
    pool sizes to 40 whole-batch rows and the fill never wraps an epoch —
    a wrapped fill would plant duplicate rows (and, sequential, permanently
    omit the tail)."""
    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=3, per_class=16)  # 48 rows
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=32, rows_per_shard=64)
    loader, _ = imagenet.open_image_loader(out, batch_size=10, shuffle=False,
                                           native=False)
    cache = imagenet.DeviceDatasetCache(loader, record_size=32, image_size=32,
                                        seed=0)
    assert cache.pool_rows == 40
    pool = np.asarray(cache._pool)
    # Sequential fill of 4 exact batches: rows are the first 40 records, each
    # exactly once.
    flat = pool.reshape(40, -1)
    assert len(np.unique(flat, axis=0)) == 40
    loader.close()


def test_device_dataset_cache_fully_cached_dataset(tmp_path):
    """A pool covering the whole dataset stops streaming (the reference
    training_dataset_cache's steady state) and keeps labels consistent."""
    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=2, per_class=6)
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=32, rows_per_shard=16)
    loader, _ = imagenet.open_image_loader(out, batch_size=4, shuffle=False,
                                           native=False)
    cache = imagenet.DeviceDatasetCache(loader, record_size=32, image_size=32,
                                        refresh_interval=1, seed=0)
    assert cache.pool_rows == 12
    pool0 = np.asarray(cache._pool)
    for _ in range(5):
        b = cache.next_batch(4)
    np.testing.assert_array_equal(np.asarray(cache._pool), pool0)  # no churn
    # image_size == record_size: assembly is identity crop; check labels align
    # with pool content through the class-color invariant.
    chan = np.asarray(b["images"]).mean(axis=(1, 2)).argmax(axis=1)
    means = np.asarray(imagenet.CHANNEL_MEANS)
    # undo mean subtraction ordering: class c is bright in channel c%3.
    assert ((chan == b["labels"] % 3)).all()
    loader.close()


def test_eval_pass_restores_and_scores(tmp_path, monkeypatch):
    """Train -> checkpoint -> `imagenet.py --eval --restore`: the eval pass
    (center crop, no flip, sequential coverage) reports high top-1 on the
    color-separable tree — the reference's is_training=False input +
    accuracy eval, driven through the benchmark CLI."""
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.checkpoint import Saver
    from autodist_tpu.models import resnet
    from autodist_tpu.strategy import AllReduce

    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=3, per_class=16)
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=40, rows_per_shard=64)

    # Train a tiny resnet on the shards and checkpoint it.
    loader, meta = imagenet.open_image_loader(out, batch_size=16, shuffle=True,
                                              seed=0, native=False)
    batcher = imagenet.AugmentingBatcher(loader, image_size=32, record_size=40,
                                         train=True, seed=0)
    cfg = resnet.ResNet50Config(num_classes=3, stage_sizes=(1, 1), width=8,
                                dtype=jnp.float32)
    model, params = resnet.init_params(cfg, image_size=32)
    loss_fn = imagenet.make_augmented_loss_fn(model, image_size=32,
                                              dtype=cfg.dtype)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(3e-3),
                       example_batch=batcher.next())
    for _ in range(40):
        step(batcher.next())
    loader.close()
    prefix = Saver().save(step.get_state(), str(tmp_path / "ckpt"))

    # Eval through the benchmark CLI against the checkpoint. The tiny config
    # must match, so monkeypatch the benchmark's model construction knobs.
    import examples.benchmark.imagenet as bench
    real_cfg = resnet.ResNet50Config
    monkeypatch.setattr(
        resnet, "ResNet50Config",
        lambda **kw: real_cfg(**{**kw, "stage_sizes": (1, 1), "width": 8,
                                 "dtype": jnp.float32}))
    top1 = bench.main(["--model", "resnet50", "--eval", "--data_dir", out,
                       "--restore", prefix, "--image_size", "32",
                       "--batch_size", "16"])
    assert top1 > 0.8, top1

    # Fresh init scores ~chance on 3 classes — restore is what carried it.
    chance = bench.main(["--model", "resnet50", "--eval", "--data_dir", out,
                         "--image_size", "32", "--batch_size", "16"])
    assert chance < 0.7, chance


def test_resnet_trains_from_disk(tmp_path):
    """End-to-end: the prepared shards feed a (tiny) ResNet through the
    augmented loss inside ad.function; loss is finite and decreasing on the
    color-separable tree."""
    from autodist_tpu import AutoDist
    from autodist_tpu.models import resnet
    from autodist_tpu.strategy import AllReduce

    tree = str(tmp_path / "tree")
    _write_tree(tree, n_classes=3, per_class=16)
    out = str(tmp_path / "shards")
    imagenet.prepare_image_shards(tree, out, record_size=40, rows_per_shard=64)
    loader, meta = imagenet.open_image_loader(out, batch_size=16, shuffle=True,
                                              seed=0, native=False)
    batcher = imagenet.AugmentingBatcher(loader, image_size=32, record_size=40,
                                         train=True, seed=0)
    cfg = resnet.ResNet50Config(num_classes=len(meta["classes"]),
                                stage_sizes=(1, 1), width=8,
                                dtype=jnp.float32)
    model, params = resnet.init_params(cfg, image_size=32)
    loss_fn = imagenet.make_augmented_loss_fn(model, image_size=32,
                                              dtype=cfg.dtype)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-3),
                       example_batch=batcher.next())
    losses = [float(step(batcher.next())) for _ in range(25)]
    loader.close()
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < losses[0], losses
