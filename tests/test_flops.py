"""FLOPs/MFU accounting (utils/flops.py): the README table's MFU column."""

import numpy as np
import optax
import pytest

from autodist_tpu.utils import flops as flops_util


def test_transformer_flops_per_token_flagship_value():
    """Pin the analytic count for the flagship bench config: ~221 MFLOPs/token
    (the number the perf docs quote)."""
    fpt = flops_util.transformer_flops_per_token(
        d_model=512, n_layers=6, d_ff=2048, vocab_size=32_000, seq_len=256)
    assert fpt == pytest.approx(221.0e6, rel=0.01)


def test_transformer_flops_scale_with_experts():
    base = flops_util.transformer_flops_per_token(256, 4, 1024, 1000, 128)
    moe = flops_util.transformer_flops_per_token(256, 4, 1024, 1000, 128,
                                                 n_experts_active=2)
    assert moe > base  # an extra active expert adds MLP flops only


def test_device_peak_flops_cpu_is_unknown_and_env_overrides(monkeypatch):
    monkeypatch.delenv("AUTODIST_PEAK_FLOPS", raising=False)
    assert flops_util.device_peak_flops() is None  # suite runs on CPU sim
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", "123e12")
    assert flops_util.device_peak_flops() == pytest.approx(123e12)


def test_mfu_and_formatting(monkeypatch):
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", "100e12")
    assert flops_util.mfu(50e12) == pytest.approx(0.5)
    assert flops_util.format_mfu(0.5) == "50.0%"
    assert flops_util.format_mfu(None) == "n/a"
    assert flops_util.mfu(None) is None


def test_train_step_flops_from_compiled_step(monkeypatch):
    """The cost-analysis path reports a plausible count for a real runner's
    compiled step (CPU backend reports flops too)."""
    import jax.numpy as jnp

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(0)
    params = {"w": rng.randn(32, 8).astype(np.float32)}
    batch = {"x": rng.randn(16, 32).astype(np.float32),
             "y": rng.randn(16, 8).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)

    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss, params, optax.sgd(0.1),
                                           example_batch=batch)
    state = runner.init(params)
    sharded = runner.shard_batch(batch)
    assert flops_util.train_step_flops(runner, state, sharded) is None  # not compiled yet
    state, _ = runner.run(state, sharded)
    fl = flops_util.train_step_flops(runner, state, sharded)
    # Cost analysis is PER-DEVICE (the SPMD module computes a 1/dp batch
    # shard) — which is what MFU against a per-device peak wants. fwd+bwd of
    # the local 2x32 @ 32x8 matmul is ~3 * 2*2*32*8 ≈ 3k flops.
    assert fl is not None and 1e3 < fl < 1e5

    peak = 1e12
    monkeypatch.setenv("AUTODIST_PEAK_FLOPS", str(peak))
    value = flops_util.report_mfu(fl, steps_per_sec=100.0)
    assert value == pytest.approx(fl * 100.0 / peak)
    assert flops_util.report_mfu(None, 100.0) is None
