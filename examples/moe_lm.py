"""Mixture-of-Experts LM training with expert parallelism.

Beyond reference parity (the reference has no MoE): expert FFN banks shard over
the mesh ``expert`` axis via the ExpertParallel strategy; XLA inserts the token
all_to_all dispatch. Throughput printed as tokens/sec.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import moe
from autodist_tpu.strategy import ExpertParallel
from autodist_tpu.utils.metrics import ThroughputMeter


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch_size", type=int, default=16)
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--d_model", type=int, default=512)
    parser.add_argument("--n_layers", type=int, default=4)
    parser.add_argument("--n_experts", type=int, default=8)
    parser.add_argument("--expert_axis", type=int, default=-1,
                        help="-1 = auto (largest divisor of devices and experts)")
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--accum", type=int, default=1,
                        help="gradient-accumulation micro-batches per step "
                             "(global batch = --batch_size; must divide it)")
    parser.add_argument("--log_every", type=int, default=50)
    parser.add_argument("--resource_spec", type=str, default=None)
    args = parser.parse_args(argv)

    import jax

    from autodist_tpu.ops import mosaic_compiles
    on_accel = jax.default_backend() != "cpu"
    cfg = moe.MoETransformerLMConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=8,
        n_layers=args.n_layers, d_ff=4 * args.d_model, max_len=args.seq_len + 1,
        n_experts=args.n_experts,
        dtype=jnp.bfloat16 if on_accel else jnp.float32,
        # Fused pallas head on Mosaic-compiling backends, like the flagship
        # bench (elsewhere pallas would run in interpret mode).
        fused_head=mosaic_compiles())

    model, params = moe.init_params(cfg)
    loss_fn = moe.make_loss_fn(model)
    batch = moe.synthetic_batch(cfg, args.batch_size, args.seq_len)

    ad = AutoDist(args.resource_spec, strategy_builder=ExpertParallel(
        num_experts=args.n_experts, expert_axis_size=args.expert_axis))
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch,
                       accumulation_steps=args.accum)

    meter = ThroughputMeter(batch_size=args.batch_size * args.seq_len,
                            log_every=args.log_every, unit="tokens")
    loss = None
    for _ in range(args.steps):
        loss = step(batch)
        meter.step(sync=loss)
    print(f"moe: final loss {float(loss):.4f}; "
          f"average {meter.average or 0:.1f} tokens/sec "
          f"(mesh={dict(step.runner.mesh.shape)})")
    # Analytic count (the fused pallas head is invisible to XLA's analysis):
    # Switch-style top-1 routing runs one expert MLP per token. Per-device
    # tokens/s against the per-device peak, like bench.py.
    import jax

    from autodist_tpu.utils import flops as flops_util
    tokens_per_step = args.batch_size * args.seq_len
    fpt = flops_util.transformer_flops_per_token(
        cfg.d_model, cfg.n_layers, cfg.d_ff, cfg.vocab_size, args.seq_len)
    flops_util.report_mfu(
        fpt * tokens_per_step / len(jax.devices()),
        (meter.average or 0) / tokens_per_step)
    return meter.average


if __name__ == "__main__":
    main()
