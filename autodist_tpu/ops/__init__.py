"""Hot-op kernels: pallas TPU kernels with pure-JAX blockwise fallbacks."""

from autodist_tpu.ops.blockwise_attention import blockwise_attention
from autodist_tpu.ops.flash_attention import flash_attention
from autodist_tpu.ops.fused_xent import fused_softmax_xent, matmul_logsumexp

__all__ = ["blockwise_attention", "flash_attention", "fused_softmax_xent",
           "matmul_logsumexp"]
