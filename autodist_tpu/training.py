"""Training loop with automatic checkpoint/resume.

The reference left the loop to user scripts (session.run loops, Keras fit) and
proved resumability with its NFS saver case — chief-gated saves on a shared
filesystem (``tests/integration/cases/c10.py:1-12``). This is that contract as
an API: periodic chief-gated saves under original names, automatic resume from
the latest checkpoint, throughput metering, and a final save — so a preempted
run restarted with the same command continues where it stopped.
"""

from typing import Any, Callable, Iterable, Optional, Union

import jax
import numpy as np

from autodist_tpu import const, telemetry
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.data import prefetch as _prefetch
from autodist_tpu.parallel import recovery as _recovery
from autodist_tpu.runner import MicroBatched, TrainState
from autodist_tpu.testing import faults as _faults
from autodist_tpu.telemetry import health as _health
from autodist_tpu.telemetry import history as _history
from autodist_tpu.telemetry import memplane as _memplane
from autodist_tpu.telemetry import openmetrics as _openmetrics
from autodist_tpu.telemetry import profiling as _profiling
from autodist_tpu.utils import logging
from autodist_tpu.utils.metrics import ThroughputMeter

PyTree = Any


def _observe_health(monitor, runner, step: int, losses,
                    state: TrainState):
    """Feed the health monitor at a log boundary (where the loss readback
    already synced) and apply the policy: ``losses`` is the period's
    per-step loss values (host-side), the bundle is the runner's latest
    device readback. Raises :class:`telemetry.HealthHalt` with the LIVE
    state attached under ``AUTODIST_HEALTH_ACTION=halt``, or
    :class:`telemetry.HealthRecover` under ``recover`` — ``train()``'s
    retry wrapper catches the latter, rolls back to the newest
    last-known-good snapshot, and resumes."""
    bundle = getattr(runner, "last_health", None)
    if bundle is not None:
        bundle = jax.device_get(bundle)
    anomalies = monitor.observe(step, losses, bundle)
    if anomalies and monitor.should_recover:
        raise _health.HealthRecover(step, state, anomalies)
    if anomalies and monitor.should_halt:
        raise _health.HealthHalt(step, state, anomalies)


def _make_meter(first_batch: PyTree, batch_size: Optional[int],
                log_every: int) -> ThroughputMeter:
    """Meter sized lazily from the first batch: the largest leading dim fixes
    the example count per step (shared by the per-step and unrolled loops so
    their examples/s can never diverge for identical configs). A batch that
    already went through ``shard_batch`` under gradient accumulation carries
    ``MicroBatched`` leaves laid out ``[k, B/k, ...]`` — fold those back to
    ``B`` (the prefetched per-step loop meters the transformed batch)."""
    n = batch_size
    if n is None:
        dims = []
        for leaf in jax.tree_util.tree_leaves(
                first_batch, is_leaf=lambda x: isinstance(x, MicroBatched)):
            if isinstance(leaf, MicroBatched):
                v = leaf.value
                if getattr(v, "ndim", 0) >= 2:
                    dims.append(v.shape[0] * v.shape[1])
            elif getattr(leaf, "ndim", 0) >= 1:
                dims.append(leaf.shape[0])
        n = max(dims, default=1)
    return ThroughputMeter(batch_size=n, log_every=log_every, log=False)


def train(runner, params: PyTree,
          batches: Union[Callable[[int], PyTree], Iterable[PyTree]],
          steps: int,
          checkpoint_dir: Optional[str] = None,
          checkpoint_name: str = "model",
          save_every: int = 1000,
          max_to_keep: int = 5,
          log_every: int = 100,
          batch_size: Optional[int] = None,
          is_chief: Optional[bool] = None,
          resume: bool = True,
          async_save: bool = False,
          on_metrics: Optional[Callable[[int, float, float], None]] = None,
          eval_every: int = 0,
          eval_batch: Any = None,
          eval_fn: Optional[Callable] = None,
          on_eval: Optional[Callable[[int, Any], None]] = None,
          unroll: Optional[int] = None,
          prefetch_depth: Optional[int] = None,
          health_monitor: Optional["_health.HealthMonitor"] = None) -> TrainState:
    """Run ``steps`` global steps, checkpointing and resuming automatically.

    ``batches``: either ``fn(step_index) -> batch`` or an iterable of batches
    (exhaustion ends the run early). In a multi-process SPMD program
    (``jax.process_count() > 1``) saves are COLLECTIVE: every process calls
    :meth:`Saver.save` at the same step, writes the state shards it owns, and
    only the chief publishes the manifest + rotation — the c10
    shared-filesystem protocol against cross-process-sharded state. With one
    process (or async-PS worker roles), saves stay chief-only.
    ``async_save=True`` makes PERIODIC saves double-buffered (device snapshot
    synchronous, file IO behind the step loop — :meth:`Saver.save`); the
    final save is always synchronous, so the returned state is durably on
    disk. ``on_metrics(step, loss, rate)`` fires every
    ``log_every`` steps. With ``eval_every`` and ``eval_batch``, the runner's
    forward-only :meth:`evaluate` runs every ``eval_every`` steps on the
    current params (``eval_fn`` defaults to the loss) and ``on_eval(step,
    value)`` receives the result. Returns the final :class:`TrainState`.

    ``unroll=None`` (the default) adopts the runner's tuned plan when one is
    attached (``create_distributed_session(tune=True)`` sets
    ``runner.tuned_plan``; its ``unroll`` is the autotuner's measured
    winner) and otherwise behaves as ``unroll=1``; pass an explicit value
    to override the tuned knob.

    ``unroll=K`` (K > 1) switches the loop to the fused dispatch-ahead
    pipeline: K consecutive batches are stacked into one pre-sharded block and
    run as ONE compiled K-step program (:meth:`DistributedRunner.run_many` —
    bit-identical to K per-step calls), while the host gathers and pre-shards
    the next block behind the running one. Checkpoint and eval cadence points
    force block boundaries, so saves/evals fire at exactly the per-step
    loop's steps and resume semantics are unchanged (step i still consumes
    batch i); only logging moves to block granularity (the first block is the
    meter's warmup, periods close at the first block end with ``log_every``
    post-warmup steps, and ``on_metrics`` receives the block's last loss).
    Runners without fused support (async-PS, remote workers) fall back to the
    per-step loop with a warning.

    ``prefetch_depth`` arms the async input pipeline
    (:mod:`autodist_tpu.data.prefetch`): a background producer pulls up to
    ``prefetch_depth`` batches (blocks, under ``unroll=K``) ahead of the
    step and applies the feed remapping (``shard_batch``/``shard_block``)
    there, so host loading and host->HBM transfer overlap the running
    step; ``train.data_wait`` then measures only the residual queue wait,
    while the ``data.producer_wait`` counter keeps naming a slow loader.
    ``None`` adopts the tuned plan's ``prefetch_depth`` when one is
    attached and nonzero, else the ``AUTODIST_PREFETCH_DEPTH`` flag
    (default 0 = the synchronous feed, batches pulled exactly at their
    step). Prefetching calls the batch source up to ``prefetch_depth``
    items ahead (an iterable may be advanced past the last consumed step
    at shutdown); exceptions from the source re-raise at the consuming
    step, and exhaustion ends the run exactly like the synchronous path.

    ``health_monitor`` overrides the ``AUTODIST_HEALTH`` default (a
    :class:`telemetry.HealthMonitor`, or the flag builds one): the monitor
    consumes each log period's per-step losses plus the runner's fused
    on-device numerics bundle at the SAME boundary where the loss readback
    already syncs — zero extra dispatches, zero extra syncs. Anomalies
    (NaN/Inf, loss spikes) become ``health.anomaly`` events and follow the
    ``AUTODIST_HEALTH_ACTION`` policy; ``halt`` raises
    :class:`telemetry.HealthHalt` carrying the live state. Monitoring needs
    ``log_every > 0`` (boundaries are where readbacks happen).

    ``AUTODIST_HEALTH_ACTION=recover`` (and its alert-engine twin
    ``AUTODIST_ALERT_ACTION=recover``) turns detection into self-healing:
    the loop keeps a bounded ring of last-known-good states captured at
    health-clean log boundaries, and an anomaly ROLLS BACK to the newest
    good one and resumes — replaying the rolled-back steps exactly when
    ``batches`` is a callable (an iterable source continues on its next
    unconsumed items instead). At most ``AUTODIST_RECOVER_MAX`` rollback
    attempts; exhaustion (or an anomaly before any healthy boundary)
    escalates to the existing :class:`telemetry.HealthHalt` /
    :class:`telemetry.AlertHalt`. See ``docs/usage/resilience.md``.
    """
    if unroll is None:
        tuned = getattr(runner, "tuned_plan", None)
        unroll = int(getattr(tuned, "unroll", 1) or 1)
        if unroll > 1:
            logging.info("train: adopting tuned plan unroll=%d (%s; pass "
                         "unroll= explicitly to override)", unroll,
                         getattr(tuned, "name", "tuned plan"))
    if unroll < 1:
        raise ValueError("unroll must be >= 1")
    if prefetch_depth is None:
        tuned = getattr(runner, "tuned_plan", None)
        tuned_depth = int(getattr(tuned, "prefetch_depth", 0) or 0)
        if tuned_depth > 0:
            logging.info("train: adopting tuned plan prefetch_depth=%d "
                         "(pass prefetch_depth= explicitly to override)",
                         tuned_depth)
            prefetch_depth = tuned_depth
        else:
            prefetch_depth = _prefetch.default_prefetch_depth()
    prefetch_depth = max(0, int(prefetch_depth))
    if eval_every and eval_batch is None:
        raise ValueError("eval_every needs an eval_batch")
    if is_chief is None:
        is_chief = const.is_chief_process()
    # Scrape endpoint: AUTODIST_METRICS_PORT attaches /metrics + /healthz to
    # the trainer process too (PSServer/InferenceServer processes attach in
    # their constructors; the process-global exporter binds once either way).
    _openmetrics.maybe_serve()
    # Sharded (multi-process SPMD) saves are collective: every process must
    # participate — each writes the shards it owns; the Saver itself gates
    # manifest/rotation to process 0. Chief-only gating remains for
    # single-process programs (incl. async-PS roles, where each process is
    # its own jax program).
    save_participant = is_chief or jax.process_count() > 1
    saver = Saver(max_to_keep=max_to_keep) if checkpoint_dir else None
    prefix_base = f"{checkpoint_dir}/{checkpoint_name}" if checkpoint_dir else None

    state = None
    if saver is not None and resume:
        latest = Saver.latest_checkpoint(checkpoint_dir, name=checkpoint_name)
        if latest is not None:
            state = saver.restore(latest, runner=runner)
            logging.info("train: resumed from %s at step %d", latest,
                         int(state.step))
    if state is None:
        state = runner.init(params)

    next_batch = batches if callable(batches) else None
    batch_iter = iter(batches) if next_batch is None else None

    start = int(state.step)
    if batch_iter is not None and start > 0:
        # Resume with an iterable: fast-forward so step i still consumes batch i —
        # replaying from item 0 would retrain on already-seen data and break the
        # identical-resume contract.
        logging.info("train: fast-forwarding batch iterator by %d consumed steps",
                     start)
        for _ in range(start):
            try:
                next(batch_iter)
            except StopIteration:
                return state
    monitor = health_monitor if health_monitor is not None \
        else _health.HealthMonitor.from_env()
    if monitor is not None and not log_every:
        logging.warning("train: health monitors need log_every > 0 (the "
                        "bundle readback rides log boundaries); disabling "
                        "them for this run")
        monitor = None
    use_blocks = (unroll > 1 and getattr(runner, "supports_run_many", False)
                  and not getattr(runner, "_is_remote_worker", False))
    if unroll > 1 and not use_blocks:
        logging.warning(
            "train: unroll=%d requested but %s has no fused multi-step path "
            "(async/remote regime); falling back to per-step dispatch",
            unroll, type(runner).__name__)

    def _finish(final_state: TrainState) -> TrainState:
        # End-of-run attribution flush (the health monitors' PR 8 contract,
        # re-established here): a final partial period — steps not a
        # multiple of log_every, or a run shorter than one period — still
        # reaches the series; require_steps drops a dispatch-less tail.
        # BEFORE the final save: a multi-second synchronous checkpoint
        # would otherwise land in the tail period's compute residual and
        # inflate the profile's period-weighted step_s.
        if _profiling.active():
            _profiling.observe_period(int(final_state.step),
                                      require_steps=True)
        # End-of-run history flush (forced past the throttle): a run shorter
        # than one min_interval_s window still leaves at least one sample —
        # and its final alert tick — in the ring/shards. AFTER the closing
        # observe_period so the sample carries the tail period's gauges;
        # BEFORE the final save so a halt-action alert stops us with the
        # state unsaved-but-LIVE on the exception, exactly like HealthHalt.
        try:
            _history.maybe_sample(int(final_state.step), reason="final",
                                  force=True)
        except telemetry.AlertHalt as e:
            e.state = final_state
            raise
        # Final save stays synchronous: train() returning means the state is
        # durably on disk (save() joins any in-flight periodic write first).
        if saver is not None and save_participant and int(final_state.step) > start:
            with telemetry.span("train.checkpoint", final=True):
                saver.save(final_state, prefix_base, runner=runner)
        if saver is not None:
            saver.wait()
        # Per-run profile store: with the attribution plane armed and
        # AUTODIST_PROFILE_DIR set, the run's profile JSON (program costs +
        # attribution series) lands on disk for adprof/costmodel.
        _profiling.maybe_write_profile()
        return final_state

    # Recover-and-resume policy (parallel/recovery.py): under
    # AUTODIST_HEALTH_ACTION=recover (or the alert-engine twin) the loops
    # push the state into a bounded last-known-good ring at every HEALTHY
    # log boundary, and an anomaly rolls back to the newest good snapshot
    # and re-enters the loop — bounded by AUTODIST_RECOVER_MAX attempts
    # before escalating to the existing halt.
    recover_armed = (
        (monitor is not None and monitor.config.action == "recover")
        or str(const.ENV.AUTODIST_ALERT_ACTION.val) == "recover")
    ring = None
    if recover_armed:
        # Ring entries must OWN their buffers: the sync runner's step
        # DONATES its input state, so a bare reference would be deleted by
        # the dispatch right after the push. One fused on-device copy per
        # healthy boundary (sharding-preserving; recover is opt-in and log
        # boundaries are sparse — the copy is the price of a rollback
        # target that survives donation).
        import jax.numpy as jnp
        ring = _recovery.SnapshotRing(copy_fn=jax.jit(
            lambda s: jax.tree_util.tree_map(jnp.copy, s)))
    if ring is not None and batch_iter is not None:
        logging.warning(
            "train: recover action with an ITERABLE batch source — a "
            "rollback cannot replay consumed batches, so the resumed loop "
            "continues on the next unconsumed ones (pass a callable "
            "batches(step) source for exact replay)")

    def _run_attempt(attempt_state: TrainState) -> TrainState:
        """One pass of the chosen loop from ``attempt_state``'s own step —
        feeds are (re)built per attempt so a rollback's replay pulls the
        rolled-back step range, not the crashed attempt's readahead."""
        start_i = int(attempt_state.step)
        if use_blocks:
            # Async input pipeline for the fused loop: the producer gathers
            # the NEXT blocks (clipped at the same cadence boundaries the
            # sync path uses) and pre-shards them (shard_block = stacking +
            # async device_put) up to prefetch_depth blocks ahead, so the
            # BatchBlock queue feeds without blocking at block assembly.
            feed = None
            if prefetch_depth > 0:
                feed = _BlockFeed(
                    runner, next_batch, batch_iter, start_i, steps, unroll,
                    _boundary_fn(steps,
                                 save_every if saver is not None else 0,
                                 eval_every), prefetch_depth)
            try:
                return _unrolled_loop(
                    runner, attempt_state, next_batch, batch_iter, start_i,
                    steps, unroll, saver, prefix_base, save_participant,
                    save_every, async_save, log_every, batch_size,
                    on_metrics, eval_every, eval_batch, eval_fn, on_eval,
                    monitor, feed, ring)
            finally:
                if feed is not None:
                    feed.close()
        # Async input pipeline: with prefetch_depth > 0 a background
        # producer pulls host batches AND applies the feed remapping
        # (shard_batch = async device_put) up to `depth` ahead, so the
        # loop's train.data_wait span measures only the residual queue
        # wait. The producer books data.producer_wait/queue_depth, keeping
        # a slow loader visible.
        feed = _step_feed(runner, next_batch, batch_iter, start_i, steps,
                          prefetch_depth) if prefetch_depth > 0 else None
        try:
            return _per_step_loop(
                runner, attempt_state, feed, next_batch, batch_iter,
                start_i, steps, saver, prefix_base, save_participant,
                save_every, async_save, log_every, batch_size, on_metrics,
                eval_every, eval_batch, eval_fn, on_eval, monitor, ring)
        finally:
            if feed is not None:
                feed.close()

    attempt = 0
    last_fail_step = None
    while True:
        try:
            state = _run_attempt(state)
            break
        except (_health.HealthRecover, telemetry.AlertRecover) as e:
            # AUTODIST_RECOVER_MAX bounds attempts PER INCIDENT, not per
            # run: an anomaly at a LATER step than the last one means the
            # earlier incident was overcome (training progressed past it),
            # so the budget resets — three transient spikes hours apart
            # must not spend a lifetime cap and turn the fourth into a
            # halt. A repeat at the same (or an unknown) step is the same
            # incident and keeps counting toward escalation.
            fail_step = getattr(e, "step", None)
            if fail_step is not None and last_fail_step is not None \
                    and fail_step > last_fail_step:
                attempt = 0
            if fail_step is not None:
                last_fail_step = fail_step
            attempt += 1
            # Returns the newest good state (re-seeding an async runner's
            # service), or escalates to HealthHalt/AlertHalt when the ring
            # is empty or AUTODIST_RECOVER_MAX is spent.
            state = _recovery.rollback(e, ring, attempt,
                                       _recovery.recover_max(),
                                       runner=runner)
    return _finish(state)


def _step_feed(runner, next_batch, batch_iter, start: int, steps: int,
               depth: int, workers: Optional[int] = None):
    """The per-step loop's async feed: a :class:`PrefetchProducer` pulling
    the batch source in step order and applying ``runner.shard_batch``
    (when the runner has one — async/remote regimes prefetch host batches
    only) on the producer side. Pulls stop at ``steps``: a callable
    source is never invoked past the last step it could train (readahead
    must not call user code out of the run's contract)."""
    if next_batch is not None:
        counter = iter(range(start, steps))
        pull = lambda: next_batch(next(counter))  # noqa: E731
    else:
        pull = lambda: next(batch_iter)           # noqa: E731
    shard = getattr(runner, "shard_batch", None)
    transform = shard if (callable(shard)
                          and not getattr(runner, "_is_remote_worker",
                                          False)) else None
    return _prefetch.PrefetchProducer(pull, transform, depth=depth,
                                      workers=workers
                                      or _prefetch.default_prefetch_workers(),
                                      name="train-feed")


def _per_step_loop(runner, state: TrainState, feed, next_batch, batch_iter,
                   start: int, steps: int, saver, prefix_base,
                   save_participant, save_every: int, async_save: bool,
                   log_every: int, batch_size: Optional[int], on_metrics,
                   eval_every: int, eval_batch, eval_fn, on_eval,
                   monitor, ring=None) -> TrainState:
    """The classic one-dispatch-per-step loop (``unroll=1``), fed either
    synchronously or from the async prefetch producer (``feed``).
    ``ring`` (a :class:`recovery.SnapshotRing`) receives the state at every
    boundary that closes healthy — the recover action's rollback targets."""
    meter = None
    loss = None
    # Health monitoring: per-step device losses accumulate here (tiny device
    # scalars, no sync) and are read back together at the log boundary — so
    # the spike detector sees EVERY step's loss while the loop still syncs
    # only once per period.
    pending_losses = []
    for step_i in range(start, steps):
        if feed is not None:
            try:
                with telemetry.span("train.data_wait"):
                    batch = next(feed)
            except StopIteration:
                logging.info("train: batch iterator exhausted at step %d",
                             step_i)
                break
        elif next_batch is not None:
            with telemetry.span("train.data_wait"):
                batch = next_batch(step_i)
        else:
            try:
                with telemetry.span("train.data_wait"):
                    batch = next(batch_iter)
            except StopIteration:
                logging.info("train: batch iterator exhausted at step %d", step_i)
                break
        if _faults.armed() and _faults.should_fire("nan_grads", step=step_i):
            # Chaos harness (testing/faults.py): NaN-fill the batch's float
            # leaves so the REAL compiled step produces real NaN gradients —
            # the recover-action tests and bench drive genuine anomalies,
            # not mocks. Un-armed cost: one module-global read per step.
            logging.warning("faults: injecting NaN batch at step %d", step_i)
            batch = _faults.corrupt_batch(batch)
        with telemetry.span("train.dispatch"):
            state, fetched = runner.run(state, batch)
        loss = fetched[0] if isinstance(fetched, tuple) else fetched
        if monitor is not None:
            pending_losses.append(loss)
        if meter is None and log_every:
            meter = _make_meter(batch, batch_size, log_every)
        if meter is not None:
            # The meter syncs (device->host read of the loss) only at its period
            # boundaries — one boundary per log_every steps, not per step — and
            # excludes its warmup step, so boundaries land at 1 + k*log_every
            # local steps.
            rate = meter.step(sync=loss)
            if rate is not None:
                # The period's attribution closes HERE — after the meter's
                # boundary sync recorded its readback span, before the
                # snapshot below is emitted — so the train.attr.*/mfu
                # gauges it books describe exactly this period.
                attr = _profiling.observe_period(step_i + 1) \
                    if _profiling.active() else None
                # Async-PS runs append their transport accounting (zero-copy
                # wire counters) so per-period logs show parameter/gradient
                # traffic next to throughput. `q` is the input queue depth
                # (the prefetch producer's fill with prefetch_depth > 0,
                # else 0 — 0 under prefetch means the loader is not keeping
                # up), `rb` the seconds this period spent blocked on
                # device->host readback — together they say whether a slow
                # period was compute, readback, or host-side stall, from
                # the log line alone.
                stats = getattr(runner, "wire_stats", None)
                stats = stats() if callable(stats) else None
                logging.info("train: step %d loss %.4f %.1f examples/s "
                             "| q %d rb %.3fs%s%s",
                             step_i + 1, float(loss), rate,
                             feed.queue_depth() if feed is not None else 0,
                             meter.last_readback_s,
                             f" | {stats.format_line()}" if stats else "",
                             _profiling.format_attr_line(attr))
                # The period's throughput as a gauge: the fleet console
                # (tools/adfleet.py) compares steps/s across processes off
                # the status opcode, so the rate must live in the registry,
                # not just the log line. One gauge set per log boundary.
                telemetry.gauge("train.steps_per_s").set(
                    round(rate / meter.batch_size, 4))
                if telemetry.enabled():
                    # Memory gauges first so the snapshot emitted below
                    # carries this boundary's live-buffer/HBM readings (and
                    # the opt-state footprint ZeRO sharding divides). The
                    # census tags re-point at THIS boundary's state — the
                    # step donates its inputs, so last boundary's claims
                    # are dead weakrefs by now.
                    _memplane.tag("params", state.params)
                    _memplane.tag("opt_state", state.opt_state)
                    telemetry.sample_device_memory(opt_state=state.opt_state)
                    telemetry.emit_metrics(global_step=step_i + 1)
                if monitor is not None:
                    _observe_health(monitor, runner, step_i + 1,
                                    jax.device_get(pending_losses), state)
                    pending_losses = []
                # Metric-history sample LAST at the boundary, so the sample
                # (and the alert rules it evaluates) sees this period's
                # attr/mfu/health/throughput gauges. An AlertHalt under
                # AUTODIST_ALERT_ACTION=halt propagates from here — the
                # train loop is the sampler a halt can actually stop — with
                # the LIVE TrainState attached (the HealthHalt contract:
                # a halt leaves the state checkpointable, not discarded).
                try:
                    _history.maybe_sample(step_i + 1)
                except telemetry.AlertHalt as e:
                    e.state = state
                    raise
                # The boundary closed HEALTHY (no health anomaly raised, no
                # alert fired past this point): this state is a valid
                # rollback target. push() DEEP-COPIES on device via the
                # ring's copy_fn — the step donates its input buffers, so a
                # bare reference would be deleted by the next dispatch.
                if ring is not None:
                    ring.push(step_i + 1, state)
                    if telemetry.enabled():
                        # Ring census: the deep-copied snapshot states are
                        # pinned device memory nothing else accounts for.
                        _memplane.tag("snapshots", ring.states())
                if on_metrics is not None:
                    on_metrics(step_i + 1, float(loss), rate)
        if (eval_every and (step_i + 1) % eval_every == 0
                and not getattr(runner, "_is_remote_worker", False)):
            # Async remote workers skip: their local state is a compile-shapes
            # template and AsyncPSRunner.evaluate raises there by design. Sync
            # SPMD processes all evaluate together (the compiled eval is a
            # collective program).
            with telemetry.span("train.eval"):
                val = runner.evaluate(state, eval_batch, eval_fn)
            try:
                logging.info("train: step %d eval %.6f", step_i + 1, float(val))
            except (TypeError, ValueError):
                logging.info("train: step %d eval (pytree)", step_i + 1)
            if on_eval is not None:
                on_eval(step_i + 1, val)
        if (saver is not None and save_participant and save_every
                and (step_i + 1) % save_every == 0 and step_i + 1 < steps):
            with telemetry.span("train.checkpoint"):
                saver.save(state, prefix_base, runner=runner,
                           async_write=async_save)

    if monitor is not None and pending_losses:
        # End-of-run flush: a NaN in the final partial period (steps not a
        # multiple of log_every) must still anomaly/snapshot/halt — the
        # monitor's contract is EVERY step observed, not every full period.
        _observe_health(monitor, runner, steps,
                        jax.device_get(pending_losses), state)
    if meter is not None:
        meter.finish()   # freeze the run clock: average stays the TRAIN rate
    return state


def _boundary_fn(steps: int, save_every: int, eval_every: int):
    """``next_boundary(i)``: the first step index after ``i`` where a block
    must END (a ``save_every``/``eval_every`` multiple, or ``steps``) — ONE
    clipping rule, shared by the sync gather and the async block feed so
    their block shapes can never diverge."""
    boundaries = [p for p in (save_every, eval_every) if p]

    def next_boundary(i: int) -> int:
        nxt = steps
        for p in boundaries:
            nxt = min(nxt, (i // p + 1) * p)
        return nxt

    return next_boundary


class _BlockFeed:
    """The unrolled loop's async block source: a :class:`PrefetchProducer`
    whose pulls gather cadence-clipped host blocks (the sync ``gather``'s
    exact clipping, via the shared boundary fn) and whose transform is
    ``runner.shard_block`` — so block assembly AND host->HBM transfer run
    ``depth`` blocks ahead of the device. A source that exhausts mid-block
    still emits the partial block (the sync path's contract: those steps
    were consumed and must train)."""

    def __init__(self, runner, next_batch, batch_iter, start: int,
                 steps: int, unroll: int, next_boundary, depth: int,
                 workers: Optional[int] = None):
        self.first_batch = None   # meter sizing; set before the first emit
        self._next_batch = next_batch
        self._batch_iter = batch_iter
        self._cursor = start
        self._steps = steps
        self._unroll = unroll
        self._next_boundary = next_boundary
        self._exhausted = False
        self._producer = _prefetch.PrefetchProducer(
            self._pull, runner.shard_block, depth=depth,
            workers=workers or _prefetch.default_prefetch_workers(),
            name="train-feed")

    def _pull(self):
        i = self._cursor
        if self._exhausted or i >= self._steps:
            raise StopIteration
        blk = []
        for j in range(min(self._unroll, self._next_boundary(i) - i)):
            if self._next_batch is not None:
                blk.append(self._next_batch(i + j))
            else:
                try:
                    blk.append(next(self._batch_iter))
                except StopIteration:
                    self._exhausted = True
                    logging.info("train: batch iterator exhausted at "
                                 "step %d", i + len(blk))
                    break
        if not blk:
            raise StopIteration
        if self.first_batch is None:
            self.first_batch = blk[0]
        self._cursor = i + len(blk)
        return blk

    def next_block(self):
        """The next pre-sharded BatchBlock, or None at the end of the run
        (exhaustion / ``steps`` reached) — the sync ``gather``'s return
        contract."""
        try:
            return next(self._producer)
        except StopIteration:
            return None

    def queue_depth(self) -> int:
        return self._producer.queue_depth()

    def close(self):
        self._producer.close()


def _unrolled_loop(runner, state: TrainState, next_batch, batch_iter,
                   start: int, steps: int, unroll: int,
                   saver, prefix_base, save_participant, save_every: int,
                   async_save: bool, log_every: int, batch_size: Optional[int],
                   on_metrics, eval_every: int, eval_batch, eval_fn,
                   on_eval, monitor=None, feed: Optional[_BlockFeed] = None,
                   ring=None) -> TrainState:
    """The fused dispatch-ahead pipeline behind ``train(..., unroll=K)``.

    Consecutive batches are gathered into blocks of up to ``unroll`` steps and
    run as one compiled K-step scan (:meth:`DistributedRunner.run_many`);
    while the device executes a block, the host gathers and pre-shards the
    next one (a one-block dispatch-ahead queue — dispatch is asynchronous, so
    the prep overlaps device compute). Blocks are clipped so they END exactly
    at every ``save_every``/``eval_every`` multiple and at ``steps``, which
    keeps checkpoint/eval/resume semantics identical to the per-step loop;
    losses are read back (``jax.device_get``) only when a ``log_every``
    period closes at a block boundary.

    With ``feed`` (a :class:`_BlockFeed`, ``train(prefetch_depth>0)``) the
    blocks arrive pre-sharded from the async producer instead of being
    gathered here: ``train.data_wait`` then measures only the residual
    queue wait, and the producer's ``data.*`` telemetry carries the loader
    cost."""
    next_boundary = _boundary_fn(steps,
                                 save_every if saver is not None else 0,
                                 eval_every)
    exhausted = False
    first_batch = None

    def gather(i: int):
        """Up to min(unroll, steps-to-next-cadence-point) host batches
        starting at step index ``i``, pre-sharded; None when the run is
        over."""
        nonlocal exhausted, first_batch
        if feed is not None:
            with telemetry.span("train.data_wait"):
                block = feed.next_block()
            if first_batch is None:
                first_batch = feed.first_batch
            return block
        if exhausted or i >= steps:
            return None
        blk = []
        with telemetry.span("train.data_wait"):
            for j in range(min(unroll, next_boundary(i) - i)):
                if next_batch is not None:
                    blk.append(next_batch(i + j))
                else:
                    try:
                        blk.append(next(batch_iter))
                    except StopIteration:
                        exhausted = True
                        logging.info("train: batch iterator exhausted at "
                                     "step %d", i + len(blk))
                        break
        if not blk:
            return None
        if first_batch is None:
            first_batch = blk[0]
        with telemetry.span("runner.shard_block"):
            return runner.shard_block(blk)

    meter = None
    step_i = start
    # Health: the period's per-block loss stacks (device [K] arrays), read
    # back together at the boundary the meter already syncs.
    pending_losses = []
    block = gather(step_i)
    while block is not None:
        with telemetry.span("train.dispatch", steps=block.length):
            state, fetched = runner.run_many(state, block)
        losses = fetched[0] if isinstance(fetched, tuple) else fetched
        if monitor is not None:
            pending_losses.append(losses)
        step_i += block.length
        # Dispatch-ahead: run_many returns as soon as the K-step program is
        # enqueued; gather + pre-shard the next block NOW, before any sync
        # below, so host batch assembly and h->d transfer overlap the device.
        next_block = gather(step_i)
        queue_depth = (1 if next_block is not None else 0) \
            + (feed.queue_depth() if feed is not None else 0)
        if telemetry.enabled():
            telemetry.gauge("train.dispatch_queue_depth").set(queue_depth)
        if meter is None and log_every:
            meter = _make_meter(first_batch, batch_size, log_every)
        if meter is not None:
            rate = meter.step_many(block.length, sync=losses)
            if rate is not None:
                # Attribution closes at the same boundary the meter synced
                # (readback span recorded), before emit_metrics ships the
                # snapshot carrying the freshly-booked attr/mfu gauges.
                attr = _profiling.observe_period(step_i) \
                    if _profiling.active() else None
                last = float(jax.device_get(losses)[-1])
                # `q`: dispatch-ahead queue depth (0 means the host failed to
                # stay ahead of the device — data-starved); `rb`: period
                # seconds blocked on loss readback.
                logging.info("train: step %d loss %.4f %.1f examples/s "
                             "| q %d rb %.3fs%s",
                             step_i, last, rate, queue_depth,
                             meter.last_readback_s,
                             _profiling.format_attr_line(attr))
                # Steps/s gauge for the fleet console (same contract as the
                # per-step loop: the registry carries the rate, not just
                # the log line).
                telemetry.gauge("train.steps_per_s").set(
                    round(rate / meter.batch_size, 4))
                if telemetry.enabled():
                    # Memory gauges first so the emitted snapshot carries
                    # this boundary's live-buffer/HBM readings (and the
                    # opt-state footprint ZeRO sharding divides); census
                    # tags re-pointed first, as in the per-step loop.
                    _memplane.tag("params", state.params)
                    _memplane.tag("opt_state", state.opt_state)
                    telemetry.sample_device_memory(opt_state=state.opt_state)
                    telemetry.emit_metrics(global_step=step_i)
                if monitor is not None:
                    flat = np.concatenate([np.asarray(l).reshape(-1) for l
                                           in jax.device_get(pending_losses)])
                    _observe_health(monitor, runner, step_i, flat, state)
                    pending_losses = []
                # History sample last: the alert tick sees this boundary's
                # freshly-booked gauges (AlertHalt propagates with the live
                # state attached, like the per-step loop).
                try:
                    _history.maybe_sample(step_i)
                except telemetry.AlertHalt as e:
                    e.state = state
                    raise
                # Healthy-boundary snapshot for the recover action (the
                # per-step loop's contract: push() deep-copies on device to
                # survive the step's buffer donation).
                if ring is not None:
                    ring.push(step_i, state)
                    if telemetry.enabled():
                        _memplane.tag("snapshots", ring.states())
                if on_metrics is not None:
                    on_metrics(step_i, last, rate)
        if eval_every and step_i % eval_every == 0:
            with telemetry.span("train.eval"):
                val = runner.evaluate(state, eval_batch, eval_fn)
            try:
                logging.info("train: step %d eval %.6f", step_i, float(val))
            except (TypeError, ValueError):
                logging.info("train: step %d eval (pytree)", step_i)
            if on_eval is not None:
                on_eval(step_i, val)
        if (saver is not None and save_participant and save_every
                and step_i % save_every == 0 and step_i < steps):
            with telemetry.span("train.checkpoint"):
                saver.save(state, prefix_base, runner=runner,
                           async_write=async_save)
        block = next_block
    if monitor is not None and pending_losses:
        # End-of-run flush (same contract as the per-step loop): the final
        # partial period's losses/bundle still reach the monitor.
        flat = np.concatenate([np.asarray(l).reshape(-1) for l
                               in jax.device_get(pending_losses)])
        _observe_health(monitor, runner, step_i, flat, state)
    if meter is not None:
        meter.finish()   # freeze the run clock: average stays the TRAIN rate
    return state
