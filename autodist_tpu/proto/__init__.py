"""Generated protobuf modules.

Regenerate after editing ``strategy.proto``::

    protoc --python_out=. autodist_tpu/proto/strategy.proto

(run from the repo root; generated ``*_pb2.py`` files are checked in).
"""
