"""Serve a Transformer LM with continuous batching — the runnable doc for
``autodist_tpu/serving`` (docs/usage/serving.md).

    PYTHONPATH=. python examples/serve_lm.py                      # tiny init'd LM
    PYTHONPATH=. python examples/serve_lm.py --checkpoint /tmp/ckpt/model \
        --d_model 768 --n_layers 12                               # trained params
    PYTHONPATH=. python examples/serve_lm.py --mode static        # bench baseline

Starts an :class:`~autodist_tpu.serving.InferenceServer` in this process,
fires ``--clients`` concurrent client threads (each its own connection, the
intended concurrency model), and prints per-phase p50/p99 plus the server's
``serve.*`` SLO counters. With ``--mode static`` the same offered load runs
under wave batching — compare the p99s to see what decode-step admission
buys (bench.py --serve gates exactly that).
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from autodist_tpu import serving
from autodist_tpu.models import transformer_lm
from autodist_tpu.testing.sanitizer import san_lock


def percentile(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else float("nan")


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--checkpoint", default=None,
                        help="checkpoint prefix to restore params from "
                             "(default: init a tiny random LM)")
    parser.add_argument("--d_model", type=int, default=64)
    parser.add_argument("--n_layers", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--max_len", type=int, default=128)
    parser.add_argument("--mode", choices=("continuous", "static"),
                        default="continuous")
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--requests", type=int, default=24,
                        help="total requests across all clients")
    parser.add_argument("--max_new", type=int, default=16)
    parser.add_argument("--temperature", type=float, default=0.0)
    args = parser.parse_args(argv)

    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=max(1, args.d_model // 32), n_layers=args.n_layers,
        d_ff=4 * args.d_model, max_len=args.max_len, dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)
    if args.checkpoint:
        from autodist_tpu.checkpoint import Saver
        params = Saver().restore(args.checkpoint, params_template=params)
        print(f"restored params from {args.checkpoint}")

    scfg = serving.ServeConfig.from_env(
        max_batch=args.max_batch, mode=args.mode,
        temperature=args.temperature)
    engine = serving.LMEngine(model, params, scfg)
    server = serving.InferenceServer(serving.Batcher(engine, scfg))
    print(f"serving {args.mode} mode, {args.max_batch} slots, buckets "
          f"{engine.buckets} on {server.address[0]}:{server.address[1]}")

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, args.vocab, size=rng.randint(4, 48))
               .astype(np.int32) for _ in range(args.requests)]

    # Warm the jit caches off the clock: one prefill per bucket the workload
    # will touch, plus decode + insert — the printed p50/p99 measure
    # serving, not compilation.
    warm = serving.ServeClient(server.address)
    try:
        for b in sorted({serving.bucket_for(len(p), engine.buckets)
                         for p in prompts}):
            if b + 2 <= args.max_len:   # a fuller bucket can't serve anyway
                warm.generate(np.arange(1, 1 + b, dtype=np.int32), 2)
    finally:
        warm.close()
    timings, errors = [], []
    lock = san_lock()

    def client_thread(worker_id):
        c = serving.ServeClient(server.address)
        try:
            for i in range(worker_id, args.requests, args.clients):
                try:
                    _, timing = c.generate(prompts[i], args.max_new,
                                           seed=i)
                    with lock:
                        timings.append(timing)
                except serving.ServeError as e:
                    with lock:
                        errors.append(str(e))
        finally:
            c.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client_thread, args=(w,))
               for w in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    print(f"\n{len(timings)}/{args.requests} requests ok, "
          f"{len(errors)} rejected, {len(timings) / wall:.1f} req/s "
          f"({args.clients} clients, wall {wall:.2f}s)")
    print(f"{'phase':>8}  {'p50 ms':>9}  {'p99 ms':>9}")
    for phase in ("queue", "prefill", "decode", "total"):
        xs = [t[f"{phase}_s"] * 1e3 for t in timings]
        print(f"{phase:>8}  {percentile(xs, 50):9.2f}  "
              f"{percentile(xs, 99):9.2f}")

    stats = server.stats_snapshot()
    reg = stats["registry"]
    print(f"\nserver: {reg.get('serve.requests.completed', 0)} completed, "
          f"{reg.get('serve.requests.rejected', 0)} rejected, "
          f"final batch_fill {reg.get('serve.batch_fill', 0.0):.2f}, "
          f"wire {stats['wire']['bytes_received']} B in / "
          f"{stats['wire']['bytes_sent']} B out")
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
