"""NCF (NeuMF) recommender benchmark — the sparse-heavy workload.

Port of reference ``examples/benchmark/ncf.py`` + ``utils/recommendation``:
MovieLens-scale NeuMF with row-sparse embedding gradients, trained under the
Parallax hybrid (embeddings -> PS placement, dense towers -> all-reduce).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import ncf
from autodist_tpu.strategy import Parallax
from autodist_tpu.utils.metrics import ThroughputMeter


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=110)
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--resource_spec", type=str, default=None)
    parser.add_argument("--ratings", type=str, default=None,
                        help="Train on a real MovieLens-format ratings file "
                             "(user,item,rating,timestamp CSV or ml-1m "
                             "::-separated .dat) with the reference's "
                             "filter/zero-index/leave-last-out protocol, then "
                             "report HR@10 / NDCG@10 on the held-out items")
    parser.add_argument("--num_neg", type=int, default=4,
                        help="training negatives per positive (--ratings)")
    args = parser.parse_args(argv)

    # NCF is gather-bound: per-step dispatch dominates at small batches, so
    # throughput scales nearly linearly with batch (v5e sweep: 172k ex/s at
    # 1024, 1.26M at 8k, 7.9M at 64k — still converging; 256k+ trains
    # unstably at this fixed lr). The reference's NCF likewise ran very large
    # batches. The default is the measured 64k GLOBAL batch whatever the
    # device count — scale explicitly (with the lr) for bigger sweeps.
    batch_size = args.batch_size or 65536

    cfg = ncf.NeuMFConfig()
    data = None
    if args.ratings:
        from autodist_tpu.data import movielens
        data = movielens.load_ratings(args.ratings)
        cfg = ncf.NeuMFConfig(num_users=data.num_users,
                              num_items=data.num_items)
        batch_size = min(batch_size, data.num_train * (1 + args.num_neg))

    model = ncf.NeuMF(cfg)
    batch = ncf.synthetic_batch(cfg, batch_size)
    import jax.numpy as jnp
    from autodist_tpu.models.common import jit_init
    params = jit_init(model, jnp.asarray(batch["users"]), jnp.asarray(batch["items"]))
    loss_fn = ncf.make_loss_fn(model)

    ad = AutoDist(args.resource_spec, Parallax())
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch)

    feed = None
    if data is not None:
        # Real interactions, the reference's per-epoch protocol: every epoch
        # re-samples fresh uniform negatives (a NEW seed), streamed through
        # the native loader for that epoch's worth of batches.
        from autodist_tpu.data import DataLoader, device_prefetch

        def epochs():
            seed = 0
            while True:
                # sample_training_epoch already shuffles the epoch; a second
                # loader-side permutation would only double host work.
                loader = DataLoader(
                    arrays=movielens.sample_training_epoch(
                        data, args.num_neg, seed=seed),
                    batch_size=batch_size, shuffle=False)
                for _ in range(max(1, loader.n_rows // batch_size)):
                    yield loader.next()
                loader.close()
                seed += 1

        feed = device_prefetch(epochs(), step.runner, depth=2)
    else:
        # Device-resident synthetic batch (measure the chip, not the link).
        batch = step.runner.shard_batch(batch)

    meter = ThroughputMeter(batch_size=batch_size, log_every=args.log_every)
    loss = None
    try:
        for _ in range(args.steps):
            loss = step(next(feed) if feed is not None else batch)
            meter.step(sync=loss)
    finally:
        if feed is not None:
            feed.close()  # stop the producer (it would keep building epochs)
    print(f"ncf: final loss {float(loss):.4f}, {meter.average or 0:.1f} examples/sec")
    if data is not None:
        from autodist_tpu.data.movielens import (hit_rate_and_ndcg,
                                                 sample_eval_negatives)
        final_params = step.runner.logical_params(step.get_state())
        apply = jax.jit(lambda u, i: model.apply({"params": final_params},
                                                 u, i))
        negatives = sample_eval_negatives(data)  # may clamp on tiny corpora
        hr, ndcg = hit_rate_and_ndcg(
            lambda u, i: apply(jnp.asarray(u), jnp.asarray(i)),
            data, k=10, batch_users=512, negatives=negatives)
        print(f"ncf eval: HR@10={hr:.4f} NDCG@10={ndcg:.4f} "
              f"({len(data.eval_users)} users, {negatives.shape[1]} "
              f"negatives each)")
    from autodist_tpu.utils import flops as flops_util
    flops_util.report_mfu(
        flops_util.train_step_flops(step.runner, step.get_state(), batch),
        (meter.average or 0) / batch_size)
    return meter.average


if __name__ == "__main__":
    main()
