"""Checkpointing — save/restore under original single-node names.

Counterpart of reference ``autodist/checkpoint/`` (``saver.py``,
``saved_model_builder.py``). The load-bearing property (reference
``checkpoint/saver.py:47-61``, verified by ``tests/integration/cases/c0.py:130-138``)
is preserved: checkpoints are written under the model's ORIGINAL parameter names as
full unsharded logical arrays, whatever the distribution strategy — so a checkpoint
written by a PartitionedPS run restores into an AllReduce run, a single-device run,
or plain host numpy.
"""

from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder

__all__ = ["Saver", "SavedModelBuilder"]
