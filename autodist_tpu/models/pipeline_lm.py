"""Pipeline-parallel Transformer LM over the mesh ``pipe`` axis.

Beyond reference parity (the reference scoped pipeline parallelism out,
``docs/design/architecture.rst:49-51``). The model is a pure-JAX functional
transformer whose block weights are *stacked* along a leading layer dimension —
the natural layout for pipelining on TPU: the ``Pipeline`` strategy shards that
dimension ``P("pipe", ...)`` so each device stores (and runs) a contiguous group
of layers, and the forward pass streams microbatches through
``parallel/pipeline.pipeline_apply`` (GPipe schedule, ``lax.ppermute`` handoffs).
Embedding, final norm, and LM head stay replicated across pipe ranks (cheap
redundant compute in exchange for zero extra communication).
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import const
from autodist_tpu.parallel.pipeline import pipelined


@dataclasses.dataclass(frozen=True)
class PipelineLMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_len: int = 1024
    n_stages: int = 4
    num_microbatches: int = 4
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_layers % self.n_stages:
            raise ValueError("n_layers must be divisible by n_stages")


def _layer_norm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + 1e-6)
    return (y * scale + bias).astype(x.dtype)


def _block_apply(p, x, config: PipelineLMConfig):
    """One pre-LN transformer block; ``p`` holds this layer's weights (no layer dim)."""
    cfg = config
    b, t, d = x.shape
    hd = d // cfg.n_heads

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = h @ p["wqkv"].astype(x.dtype)                      # [B, T, 3D]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, cfg.n_heads, hd)
    k = k.reshape(b, t, cfg.n_heads, hd)
    v = v.reshape(b, t, cfg.n_heads, hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd).astype(np.float32)
    causal = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(causal, scores.astype(jnp.float32), -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, d)
    x = x + ctx @ p["wo"].astype(x.dtype)

    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(h @ p["w1"].astype(x.dtype))
    return x + h @ p["w2"].astype(x.dtype)


class PipelineLM:
    """Functional model object: ``apply(params, tokens) -> logits``."""

    def __init__(self, config: PipelineLMConfig):
        self.config = config

    def apply(self, params, tokens):
        cfg = self.config
        b, t = tokens.shape
        m = cfg.num_microbatches
        if b % m:
            raise ValueError(f"batch {b} not divisible by num_microbatches {m}")

        x = params["embed"][tokens].astype(cfg.dtype)
        x = x + params["pos"][None, :t, :].astype(cfg.dtype)

        # [B, T, D] -> [M, B/M, T, D]: split the batch into microbatches with the
        # microbatch index outermost-within-batch so the data sharding stays on the
        # per-microbatch batch dim.
        x_mb = x.reshape(b // m, m, t, cfg.d_model).swapaxes(0, 1)

        # [L, ...] block stacks -> [S, L/S, ...] stage groups (contiguous layers).
        lps = cfg.n_layers // cfg.n_stages
        stage_params = jax.tree_util.tree_map(
            lambda a: a.reshape(cfg.n_stages, lps, *a.shape[1:]), params["blocks"])

        def stage_fn(p, xb):
            p = jax.tree_util.tree_map(lambda a: a[0], p)  # drop stage shard dim
            def body(carry, layer_p):
                return _block_apply(layer_p, carry, cfg), None
            out, _ = jax.lax.scan(body, xb, p)
            return out

        y_mb = pipelined(stage_fn, cfg.n_stages, axis=const.MESH_AXIS_PIPE)(
            stage_params, x_mb)

        h = y_mb.swapaxes(0, 1).reshape(b, t, cfg.d_model)
        h = _layer_norm(h, params["ln_f_s"], params["ln_f_b"])
        return h.astype(jnp.float32) @ params["head"]


def make_loss_fn(model: PipelineLM):
    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = model.apply(params, inputs)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
        return nll.mean()

    return loss_fn


def init_params(config: PipelineLMConfig, rng: Optional[jax.Array] = None):
    cfg = config
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    keys = jax.random.split(rng, 8)
    d, f, l, v = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab_size

    def normal(key, shape, scale):
        return (scale * jax.random.normal(key, shape)).astype(jnp.float32)

    params = {
        "embed": normal(keys[0], (v, d), 0.02),
        "pos": normal(keys[1], (cfg.max_len, d), 0.02),
        "blocks": {
            "ln1_s": jnp.ones((l, d), jnp.float32),
            "ln1_b": jnp.zeros((l, d), jnp.float32),
            "wqkv": normal(keys[2], (l, d, 3 * d), d ** -0.5),
            "wo": normal(keys[3], (l, d, d), d ** -0.5),
            "ln2_s": jnp.ones((l, d), jnp.float32),
            "ln2_b": jnp.zeros((l, d), jnp.float32),
            "w1": normal(keys[4], (l, d, f), d ** -0.5),
            "w2": normal(keys[5], (l, f, d), f ** -0.5),
        },
        "ln_f_s": jnp.ones((d,), jnp.float32),
        "ln_f_b": jnp.zeros((d,), jnp.float32),
        "head": normal(keys[6], (d, v), d ** -0.5),
    }
    return PipelineLM(cfg), params


def sequential_apply(model: PipelineLM, params, tokens):
    """Reference forward without the pipeline (for parity tests): same math, plain
    layer loop."""
    cfg = model.config
    _, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    x = x + params["pos"][None, :t, :].astype(cfg.dtype)
    for i in range(cfg.n_layers):
        layer_p = jax.tree_util.tree_map(lambda a, i=i: a[i], params["blocks"])
        x = _block_apply(layer_p, x, cfg)
    x = _layer_norm(x, params["ln_f_s"], params["ln_f_b"])
    return x.astype(jnp.float32) @ params["head"]


def synthetic_batch(config: PipelineLMConfig, batch_size: int, seq_len: int,
                    seed: int = 0):
    rng = np.random.RandomState(seed)
    return {"tokens": rng.randint(0, config.vocab_size,
                                  size=(batch_size, seq_len + 1)).astype(np.int32)}
