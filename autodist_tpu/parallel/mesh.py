"""Device-mesh construction from a ResourceSpec.

The reference reified "where replicas live" as a list of device strings inside the
strategy (``strategy.proto:62-68``) resolved to TF device names
(``kernel/device/resolver.py:38-67``). The TPU-native design replaces both with a named
:class:`jax.sharding.Mesh`: data-parallel replicas are coordinates along the ``data``
axis, PS/weight-update sharding lives on ``reduce``, variable partitioning on ``model``,
sequence/context parallelism on ``seq``, expert parallelism on ``expert``, pipeline
stages on ``pipe``. Collectives ride ICI within a slice and DCN across slices; XLA
inserts them from shardings.
"""

import collections
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from autodist_tpu import const
from autodist_tpu.utils import logging

# Canonical axis order. Axes the user does not size default to 1 so that any
# PartitionSpec naming them is always valid.
STANDARD_AXES = (
    const.MESH_AXIS_DATA,
    const.MESH_AXIS_REDUCE,
    const.MESH_AXIS_MODEL,
    const.MESH_AXIS_SEQ,
    const.MESH_AXIS_EXPERT,
    const.MESH_AXIS_PIPE,
)


def standard_mesh_shape(n_devices: int, axes: Optional[Dict[str, int]] = None) -> "collections.OrderedDict":
    """Resolve a possibly-partial axis-size dict into a full OrderedDict over STANDARD_AXES.

    A value of ``-1`` (or an unspecified ``data`` axis) absorbs the remaining devices.
    Raises if the product does not match ``n_devices``.
    """
    axes = dict(axes or {})
    unknown = set(axes) - set(STANDARD_AXES)
    if unknown:
        raise ValueError(f"Unknown mesh axes {sorted(unknown)}; valid: {STANDARD_AXES}")

    shape = collections.OrderedDict((a, int(axes.get(a, 1))) for a in STANDARD_AXES)
    if const.MESH_AXIS_DATA not in axes:
        shape[const.MESH_AXIS_DATA] = -1
    bad = {a: s for a, s in shape.items() if s != -1 and s < 1}
    if bad:
        raise ValueError(f"Mesh axis sizes must be >= 1 (or -1 to fill), got {bad}")

    fill_axes = [a for a, s in shape.items() if s == -1]
    if len(fill_axes) > 1:
        raise ValueError(f"At most one -1 axis allowed, got {fill_axes}")
    fixed = int(np.prod([s for s in shape.values() if s != -1]))
    if fill_axes:
        if n_devices % fixed != 0:
            raise ValueError(
                f"Cannot fill axis {fill_axes[0]}: {n_devices} devices not divisible by {fixed}")
        shape[fill_axes[0]] = n_devices // fixed
    elif fixed != n_devices:
        raise ValueError(f"Mesh axes {dict(shape)} require {fixed} devices, have {n_devices}")
    return shape


def build_mesh(resource_spec=None, axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """Build the global device mesh.

    ``axes`` overrides the ResourceSpec's ``mesh:`` section. ``devices`` defaults to all
    global JAX devices (multi-host: every process passes the same global list, standard
    SPMD). Uses :func:`mesh_utils.create_device_mesh` on real TPU platforms so the mesh
    layout follows the physical ICI topology; falls back to a plain reshape on CPU sim.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axes is None and resource_spec is not None:
        axes = resource_spec.mesh_config
    shape = standard_mesh_shape(len(devices), axes)
    dims = tuple(shape.values())

    platform = devices[0].platform
    # "axon" is the tunneled-TPU PJRT plugin this image runs on — same physical
    # ICI topology concerns as the native "tpu" platform (flash-attention's
    # backend check treats it the same way, ops/flash_attention.py).
    if platform in ("tpu", "axon"):
        try:
            dev_array = mesh_utils.create_device_mesh(dims, devices=devices)
        except (ValueError, AssertionError):
            dev_array = np.asarray(devices).reshape(dims)
    else:
        dev_array = np.asarray(devices).reshape(dims)

    mesh = Mesh(dev_array, tuple(shape.keys()))
    logging.debug("Built mesh %s over %d %s device(s)", dict(shape), len(devices), platform)
    return mesh


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[const.MESH_AXIS_DATA]


def single_device_mesh() -> Mesh:
    """A 1-device mesh (used to run the original single-node step for parity checks)."""
    return build_mesh(devices=[jax.devices()[0]], axes={const.MESH_AXIS_DATA: 1})
