"""Hot-op kernels: pallas TPU kernels with pure-JAX blockwise fallbacks."""

from autodist_tpu.ops.blockwise_attention import blockwise_attention
from autodist_tpu.ops.flash_attention import flash_attention

__all__ = ["blockwise_attention", "flash_attention"]
