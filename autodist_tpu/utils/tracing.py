"""Tracing and compilation-stage snapshots.

Parity with reference §5.1:

- Chrome-trace timelines (``runner.py:66-75``, ``/tmp/autodist/traces/...``) map to
  :func:`trace`, a ``jax.profiler.trace`` wrapper writing a Perfetto/TensorBoard
  trace under the working dir's ``traces/``.
- Graph-evolution snapshots (``utils/visualization_util.py:24-36`` wrote the graph
  at each transform stage) map to :func:`dump_stage`: the jaxpr and StableHLO text
  of the train step at each compilation stage, written under ``graphs/<tag>/``.

``trace(..., with_host_spans=True)`` additionally records the host-side
telemetry spans (:mod:`autodist_tpu.telemetry`) for the traced window and
writes them as ``host_spans_w<process-id>.json`` inside the same trace
directory (the AUTODIST_PROCESS_ID suffix keeps per-worker files on a shared
trace dir from overwriting each other) — open the profiler's
``*.trace.json.gz`` and the host-span file(s) together in ui.perfetto.dev
(Perfetto merges multiple opened files into one timeline) to see host
dispatch/wait spans next to device execution. The two traces use different
clock origins, so align on a recognizable boundary (e.g. the first
``runner.run.dispatch`` span vs the first device program) rather than
absolute timestamps; for a CLOCK-ALIGNED multi-worker host timeline use
``telemetry.collect_cluster_trace`` / ``tools/tracedump.py`` instead; see
docs/usage/observability.md.
"""

import contextlib
import itertools
import os
import time
from typing import Optional

from autodist_tpu import const
from autodist_tpu.utils import logging

# Monotonic per-process suffix for default trace dirs: a wall-clock-second
# name alone collides when two traces start within the same second (the
# second trace silently wrote into — and interleaved with — the first's dir).
_TRACE_SEQ = itertools.count()


def _unique_trace_dir(name: str) -> str:
    """Collision-free default trace directory under the working dir."""
    return os.path.join(const.DEFAULT_TRACE_DIR,
                        f"{name}_{int(time.time())}_{next(_TRACE_SEQ):03d}")


@contextlib.contextmanager
def trace(name: str = "trace", trace_dir: Optional[str] = None,
          with_host_spans: bool = False):
    """Profile the enclosed steps: ``with tracing.trace(): runner.run(...)``.

    Produces a Perfetto-compatible trace viewable in TensorBoard or ui.perfetto.dev
    (the chrome-trace timeline counterpart). With ``with_host_spans=True``,
    telemetry span recording is enabled for the window and the host timeline
    is written to ``<trace_dir>/host_spans_w<process-id>.json`` on exit
    (telemetry returns to its prior enabled/disabled state afterwards; the
    per-process name keeps workers sharing a trace dir from colliding) —
    load both files in Perfetto for a host+device overlay (see module
    docstring)."""
    import jax
    trace_dir = trace_dir or _unique_trace_dir(name)
    os.makedirs(trace_dir, exist_ok=True)
    logging.info("Writing profiler trace to %s", trace_dir)
    if with_host_spans:
        from autodist_tpu import telemetry
        was_enabled = telemetry.enabled()
        # Window stamp BEFORE enabling: host_spans.json carries only spans
        # started inside this trace window, not whatever an earlier window
        # (or an always-enabled process) left in the ring.
        window_start_ns = time.perf_counter_ns()
        telemetry.enable()
    try:
        with jax.profiler.trace(trace_dir):
            yield trace_dir
    finally:
        if with_host_spans:
            if not was_enabled:
                telemetry.disable()
            telemetry.export_chrome_trace(
                os.path.join(
                    trace_dir,
                    f"host_spans_w{const.ENV.AUTODIST_PROCESS_ID.val}.json"),
                since_ns=window_start_ns)


def dump_stage(tag: str, stage: str, fn, *example_args,
               dump_dir: Optional[str] = None) -> Optional[str]:
    """Write the jaxpr + StableHLO of ``fn(*example_args)`` for one build stage.

    Stages mirror the reference's four snapshots (0-original, 1-after-partition,
    2-after-in-graph, 3-transformed): here typically "0-original" (user loss fn)
    and "1-distributed" (the sharded train step).
    """
    import jax
    dump_dir = dump_dir or os.path.join(const.DEFAULT_GRAPH_DUMP_DIR, tag)
    os.makedirs(dump_dir, exist_ok=True)
    base = os.path.join(dump_dir, stage)
    try:
        jaxpr = jax.make_jaxpr(fn)(*example_args)
        with open(base + ".jaxpr.txt", "w") as f:
            f.write(str(jaxpr))
        lowered = jax.jit(fn).lower(*example_args)
        with open(base + ".stablehlo.txt", "w") as f:
            f.write(lowered.as_text())
        logging.debug("Dumped %s stage %s", tag, stage)
        return base
    except Exception as e:  # diagnostics must never break training
        logging.warning("Stage dump %s/%s (dump path %s.*) failed: %s",
                        tag, stage, base, e)
        return None
