"""Production serving plane: continuous-batching inference on the zero-copy
wire (docs/usage/serving.md).

The repo trains 12 model families; this package serves them. Three layers,
one subsystem:

- :mod:`autodist_tpu.serving.batcher` — request queue + continuous/static
  batching loop (jax-free host core; ``ServeConfig`` knobs, bucketed prompt
  padding, decode-step-granularity admission, early-exit slot reuse).
- :mod:`autodist_tpu.serving.runtime` — model runtime adapters:
  ``LMEngine`` drives the Transformer LM's prefill+decode KV-cache path with
  a shared multi-slot cache; ``ApplyEngine`` jit-applies the stateless
  classifier/recommender families over padded batches.
- :mod:`autodist_tpu.serving.transport` — ``InferenceServer`` /
  ``ServeClient`` speaking new ``generate``/``infer``/``stats``/``ping``
  opcodes on the PR 2 scatter-gather wire (GL006-covered dispatch).

SLO metrics (``serve.latency_s.*`` ms-bucket histograms, queue/batch gauges,
request counters) ride :mod:`autodist_tpu.telemetry`; spans appear in the
PR 5 cluster trace as ``serve.*``.

Typical wiring (see ``examples/serve_lm.py``)::

    config = serving.ServeConfig.from_env(max_batch=8)
    engine = serving.LMEngine(model, params, config)
    server = serving.InferenceServer(serving.Batcher(engine, config))
    client = serving.ServeClient("%s:%d" % server.address)
    tokens, timing = client.generate(prompt, max_new_tokens=32)
"""

from autodist_tpu.serving.batcher import (ApplyBatcher, Batcher, ServeConfig,
                                          ServeError, ServeRequest,
                                          bucket_for, default_buckets,
                                          pad_prompt)
from autodist_tpu.serving.runtime import ApplyEngine, LMEngine
from autodist_tpu.serving.transport import InferenceServer, ServeClient

__all__ = [
    "ServeConfig", "ServeError", "ServeRequest",
    "Batcher", "ApplyBatcher", "LMEngine", "ApplyEngine",
    "InferenceServer", "ServeClient",
    "bucket_for", "default_buckets", "pad_prompt",
]
