"""Fused LM-head softmax cross-entropy — pallas TPU kernels.

The separable-head formulation of the LM loss is

    nll_n = lse_n - true_logit_n,   lse_n = logsumexp_v(h_n . w_v + b_v)

where the [N, V] logits tensor (4.2 GB at the flagship's N=65k, V=32k, bf16) is
pure intermediate: XLA materializes it out of the head matmul, reads it for the
log-softmax reductions, and reads/writes it again for d(logits) in the backward
— the single largest HBM consumer in the training step. These kernels compute
``lse`` (and its VJP) **without ever materializing logits in HBM**: each
[n-block, v-block] logits tile lives only in VMEM, reduced on the fly with the
same online-logsumexp state machine as the flash-attention kernel
(``ops/flash_attention.py``), and the backward recomputes tiles from the saved
``lse`` exactly like flash attention recomputes scores (FlashAttention-2 style).
The true-logit term is a cheap gather-einsum left to XLA.

``w`` is accepted in either layout — ``[D, V]`` (flax Dense kernel) or
``[V, H]`` (the reference's softmax_w; ``w_layout="vd"``) — and is cast to the
activation dtype **per tile inside the kernel**, so no transposed or downcast
copy of a multi-GiB table is ever materialized, and its gradient comes back in
the stored layout/dtype directly.

Three kernels:
- forward: grid (n-blocks, v-blocks); VMEM scratch carries (m, l) across the v
  dimension; last v-block writes ``lse = m + log l``.
- d(h):    grid (n-blocks, v-blocks); accumulates g*p @ w^T tiles in VMEM.
- d(w,b):  grid (v-blocks, n-blocks); accumulates h^T @ g*p and column-sums.

When to use (measured on a v5e chip): at the flagship size (N=65k, V=32k) this
is throughput-parity with XLA (73 vs 69 ms for loss+grads — the two backward
logit recomputes cost what the avoided HBM traffic saves), so the dense-head
models keep the XLA path. The win is **memory**: nothing here scales with N*V,
so configurations whose logits cannot exist run fine — measured: V=262k
(32 GiB of logits) and N=262k (16 GiB) both train where XLA OOMs, and the
lm1b example trains its exact 793,471-word vocabulary with the TRUE softmax
objective (48 GiB of logits if materialized; the reference needed sampled
softmax) at ~38k words/s/chip end to end.

On non-TPU backends the kernels run in pallas interpret mode, so the CPU-sim
test mesh exercises the same code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.ops.blockwise_attention import NEG_INF
from autodist_tpu.ops.flash_attention import _use_interpret

_LANES = 128
DEFAULT_N_BLOCK = 512
DEFAULT_V_BLOCK = 1024
# Padding rows' lse: large POSITIVE so exp(logits - lse) underflows to exactly 0
# whatever the bias — padding with 0 would overflow exp for bias values > ~88
# and poison dw/db with NaN through inf * 0.
_PAD_LSE = 1e30


def _logits_tile(h_ref, w_ref, b_ref, w_vd: bool):
    """([bn, bv] f32 logits tile, cast w tile). The single place the per-tile
    activation-dtype cast happens — w is contracted per its stored layout with
    no HBM copy of the table."""
    wt = w_ref[...].astype(h_ref.dtype)
    dims = (((1,), (1,)), ((), ())) if w_vd else (((1,), (0,)), ((), ()))
    logits = jax.lax.dot_general(h_ref[...], wt, dims,
                                 preferred_element_type=jnp.float32)
    return logits + b_ref[0][None, :], wt


# ------------------------------------------------------------------- forward

def _fwd_kernel(h_ref, w_ref, b_ref, lse_ref, m_ref, l_ref, *, n_v: int,
                w_vd: bool):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    logits, _ = _logits_tile(h_ref, w_ref, b_ref, w_vd)       # [bn, bv]
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
    p = jnp.exp(logits - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_prev * jnp.exp(m_prev - m_new) + p.sum(axis=-1, keepdims=True),
        l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(vi == n_v - 1)
    def _finish():
        lse_ref[0, ni, :] = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))


def _pad_inputs(h, w, b, bn, bv, w_vd: bool):
    n, d = h.shape
    v = w.shape[0] if w_vd else w.shape[1]
    n_n, n_v = pl.cdiv(n, bn), pl.cdiv(v, bv)
    if n_n * bn - n:
        h = jnp.pad(h, ((0, n_n * bn - n), (0, 0)))
    if n_v * bv - v:
        pad_v = ((0, n_v * bv - v), (0, 0)) if w_vd else ((0, 0), (0, n_v * bv - v))
        w = jnp.pad(w, pad_v)
        # Padded vocab columns get a -inf bias: exp -> 0, invisible to the lse.
        b = jnp.pad(b, (0, n_v * bv - v), constant_values=NEG_INF)
    return h, w, b.reshape(1, -1), n_n, n_v


def _w_spec(d, bv, w_vd, index2):
    """BlockSpec for one vocab tile of w in its stored layout. ``index2`` maps
    grid coords to the vocab-block index."""
    if w_vd:
        return pl.BlockSpec((bv, d), lambda *a: (index2(*a), 0))
    return pl.BlockSpec((d, bv), lambda *a: (0, index2(*a)))


def _forward(h, w, b, bn, bv, interpret, w_vd):
    n, d = h.shape
    hp, wp, bp, n_n, n_v = _pad_inputs(h, w, b, bn, bv, w_vd)
    lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n_v=n_v, w_vd=w_vd),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            _w_spec(d, bv, w_vd, lambda i, j: j),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
        ],
        # Whole [n_n, bn] plane resident (a [1, bn] block violates TPU tiling);
        # 4 bytes/row — same layout rationale as the flash kernel's lse.
        out_specs=pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, n_n, bn), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(hp, wp, bp)
    return lse.reshape(n_n * bn)[:n]


# ------------------------------------------------------------------ backward

def _dh_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dh_ref, acc_ref, *, n_v: int,
               w_vd: bool):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits, wt = _logits_tile(h_ref, w_ref, b_ref, w_vd)
    lse = lse_ref[0, ni, :]                                   # [bn]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]  # [bn, bv]
    dims = (((1,), (0,)), ((), ())) if w_vd else (((1,), (1,)), ((), ()))
    acc_ref[:] += jax.lax.dot_general(
        gp.astype(wt.dtype), wt, dims,
        preferred_element_type=jnp.float32)                   # [bn, d]

    @pl.when(vi == n_v - 1)
    def _finish():
        dh_ref[...] = acc_ref[:].astype(dh_ref.dtype)


def _dwdb_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dw_ref, db_ref,
                 dw_acc, db_acc, *, n_n: int, w_vd: bool):
    ni = pl.program_id(1)  # read at top level: program_id is invalid inside when-bodies in interpret mode

    @pl.when(ni == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    logits, _ = _logits_tile(h_ref, w_ref, b_ref, w_vd)       # [bn, bv]
    lse = lse_ref[0, ni, :]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]
    gph = gp.astype(h_ref.dtype)
    if w_vd:
        dw_acc[:] += jax.lax.dot_general(                     # [bv, d]
            gph, h_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        dw_acc[:] += jax.lax.dot_general(                     # [d, bv]
            h_ref[...], gph, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    db_acc[:, :] += jnp.broadcast_to(gp.sum(axis=0)[None, :], db_acc.shape)

    @pl.when(ni == n_n - 1)
    def _finish():
        dw_ref[...] = dw_acc[:].astype(dw_ref.dtype)
        db_ref[...] = db_acc[:1, :].astype(db_ref.dtype)


def _backward(h, w, b, lse, g, bn, bv, interpret, w_vd):
    n, d = h.shape
    v = w.shape[0] if w_vd else w.shape[1]
    hp, wp, bp, n_n, n_v = _pad_inputs(h, w, b, bn, bv, w_vd)
    lse_p = jnp.pad(lse, (0, n_n * bn - n),
                    constant_values=_PAD_LSE).reshape(1, n_n, bn)
    # Padding rows must contribute nothing: their incoming gradient pads as zero
    # AND their lse pads large-positive so exp underflows (see _PAD_LSE).
    g_p = jnp.pad(g.astype(jnp.float32), (0, n_n * bn - n)).reshape(1, n_n, bn)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, n_v=n_v, w_vd=w_vd),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            _w_spec(d, bv, w_vd, lambda i, j: j),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_n * bn, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(hp, wp, bp, lse_p, g_p)[:n]

    dw_shape = (n_v * bv, d) if w_vd else (d, n_v * bv)
    dw_scratch = pltpu.VMEM((bv, d) if w_vd else (d, bv), jnp.float32)
    dw, db = pl.pallas_call(
        functools.partial(_dwdb_kernel, n_n=n_n, w_vd=w_vd),
        grid=(n_v, n_n),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            _w_spec(d, bv, w_vd, lambda j, i: j),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
        ],
        out_specs=(
            _w_spec(d, bv, w_vd, lambda j, i: j),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(dw_shape, w.dtype),
            jax.ShapeDtypeStruct((1, n_v * bv), jnp.float32),
        ),
        scratch_shapes=[
            dw_scratch,
            pltpu.VMEM((_LANES, bv), jnp.float32),
        ],
        interpret=interpret,
    )(hp, wp, bp, lse_p, g_p)
    dw = dw[:v, :] if w_vd else dw[:, :v]
    return dh, dw, db[0, :v]


# ----------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def matmul_logsumexp(h, w, b, n_block: int = DEFAULT_N_BLOCK,
                     v_block: int = DEFAULT_V_BLOCK,
                     interpret: bool = None, w_layout: str = "dv"):
    """``logsumexp(h @ w + b, axis=-1)`` without materializing the logits.

    h: [N, D] (bf16/f32); w: [D, V] (``w_layout="dv"``, flax Dense kernel) or
    [V, D] (``w_layout="vd"``, reference softmax_w layout); b: [V] or None.
    Returns f32 [N]. Differentiable in h, w, b (custom VJP recomputes logits
    tiles from the saved lse); dw returns in w's stored layout and dtype.
    """
    lse, _ = _mls_fwd(h, w, b, n_block, v_block, interpret, w_layout)
    return lse


def _w_vd(w_layout: str) -> bool:
    if w_layout not in ("dv", "vd"):
        raise ValueError(f"w_layout must be 'dv' or 'vd', got {w_layout!r}")
    return w_layout == "vd"


def _mls_fwd(h, w, b, n_block, v_block, interpret, w_layout):
    if interpret is None:
        interpret = _use_interpret()
    w_vd = _w_vd(w_layout)
    has_bias = b is not None
    v = w.shape[0] if w_vd else w.shape[1]
    bvec = b if has_bias else jnp.zeros((v,), jnp.float32)
    lse = _forward(h, w, bvec, n_block, v_block, interpret, w_vd)
    return lse, (h, w, bvec, lse, has_bias)


def _mls_bwd(n_block, v_block, interpret, w_layout, res, g):
    if interpret is None:
        interpret = _use_interpret()
    h, w, bvec, lse, has_bias = res
    dh, dw, db = _backward(h, w, bvec, lse, g, n_block, v_block, interpret,
                           _w_vd(w_layout))
    return dh, dw, (db if has_bias else None)


matmul_logsumexp.defvjp(_mls_fwd, _mls_bwd)


def fused_softmax_xent(h, w, targets, b=None, n_block: int = DEFAULT_N_BLOCK,
                       v_block: int = DEFAULT_V_BLOCK,
                       w_layout: str = "dv") -> jax.Array:
    """Per-row NLL of ``targets`` under ``softmax(h @ w + b)`` — the fused-head
    loss. h: [N, D], w per ``w_layout``, targets: int [N]. Returns f32 [N].

    The lse term runs through the pallas kernels; the true-logit term is a
    gather-einsum XLA handles well (its grad is the row-sparse scatter).
    """
    lse = matmul_logsumexp(h, w, b, n_block, v_block, None, w_layout)
    if _w_vd(w_layout):
        w_true = jnp.take(w, targets, axis=0).astype(h.dtype)   # [N, D]
        true_logit = jnp.einsum("nd,nd->n", h, w_true,
                                preferred_element_type=jnp.float32)
    else:
        w_true = jnp.take(w, targets, axis=1).astype(h.dtype)   # [D, N]
        true_logit = jnp.einsum("nd,dn->n", h, w_true,
                                preferred_element_type=jnp.float32)
    if b is not None:
        true_logit = true_logit + b[targets]
    return lse - true_logit
