"""Training loop with automatic checkpoint/resume.

The reference left the loop to user scripts (session.run loops, Keras fit) and
proved resumability with its NFS saver case — chief-gated saves on a shared
filesystem (``tests/integration/cases/c10.py:1-12``). This is that contract as
an API: periodic chief-gated saves under original names, automatic resume from
the latest checkpoint, throughput metering, and a final save — so a preempted
run restarted with the same command continues where it stopped.
"""

from typing import Any, Callable, Iterable, Optional, Union

import jax

from autodist_tpu import const
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.runner import TrainState
from autodist_tpu.utils import logging
from autodist_tpu.utils.metrics import ThroughputMeter

PyTree = Any


def train(runner, params: PyTree,
          batches: Union[Callable[[int], PyTree], Iterable[PyTree]],
          steps: int,
          checkpoint_dir: Optional[str] = None,
          checkpoint_name: str = "model",
          save_every: int = 1000,
          max_to_keep: int = 5,
          log_every: int = 100,
          batch_size: Optional[int] = None,
          is_chief: Optional[bool] = None,
          resume: bool = True,
          async_save: bool = False,
          on_metrics: Optional[Callable[[int, float, float], None]] = None,
          eval_every: int = 0,
          eval_batch: Any = None,
          eval_fn: Optional[Callable] = None,
          on_eval: Optional[Callable[[int, Any], None]] = None) -> TrainState:
    """Run ``steps`` global steps, checkpointing and resuming automatically.

    ``batches``: either ``fn(step_index) -> batch`` or an iterable of batches
    (exhaustion ends the run early). In a multi-process SPMD program
    (``jax.process_count() > 1``) saves are COLLECTIVE: every process calls
    :meth:`Saver.save` at the same step, writes the state shards it owns, and
    only the chief publishes the manifest + rotation — the c10
    shared-filesystem protocol against cross-process-sharded state. With one
    process (or async-PS worker roles), saves stay chief-only.
    ``async_save=True`` makes PERIODIC saves double-buffered (device snapshot
    synchronous, file IO behind the step loop — :meth:`Saver.save`); the
    final save is always synchronous, so the returned state is durably on
    disk. ``on_metrics(step, loss, rate)`` fires every
    ``log_every`` steps. With ``eval_every`` and ``eval_batch``, the runner's
    forward-only :meth:`evaluate` runs every ``eval_every`` steps on the
    current params (``eval_fn`` defaults to the loss) and ``on_eval(step,
    value)`` receives the result. Returns the final :class:`TrainState`.
    """
    if eval_every and eval_batch is None:
        raise ValueError("eval_every needs an eval_batch")
    if is_chief is None:
        is_chief = const.is_chief_process()
    # Sharded (multi-process SPMD) saves are collective: every process must
    # participate — each writes the shards it owns; the Saver itself gates
    # manifest/rotation to process 0. Chief-only gating remains for
    # single-process programs (incl. async-PS roles, where each process is
    # its own jax program).
    save_participant = is_chief or jax.process_count() > 1
    saver = Saver(max_to_keep=max_to_keep) if checkpoint_dir else None
    prefix_base = f"{checkpoint_dir}/{checkpoint_name}" if checkpoint_dir else None

    state = None
    if saver is not None and resume:
        latest = Saver.latest_checkpoint(checkpoint_dir, name=checkpoint_name)
        if latest is not None:
            state = saver.restore(latest, runner=runner)
            logging.info("train: resumed from %s at step %d", latest,
                         int(state.step))
    if state is None:
        state = runner.init(params)

    next_batch = batches if callable(batches) else None
    batch_iter = iter(batches) if next_batch is None else None

    start = int(state.step)
    if batch_iter is not None and start > 0:
        # Resume with an iterable: fast-forward so step i still consumes batch i —
        # replaying from item 0 would retrain on already-seen data and break the
        # identical-resume contract.
        logging.info("train: fast-forwarding batch iterator by %d consumed steps",
                     start)
        for _ in range(start):
            try:
                next(batch_iter)
            except StopIteration:
                return state
    meter = None
    loss = None
    for step_i in range(start, steps):
        if next_batch is not None:
            batch = next_batch(step_i)
        else:
            try:
                batch = next(batch_iter)
            except StopIteration:
                logging.info("train: batch iterator exhausted at step %d", step_i)
                break
        state, fetched = runner.run(state, batch)
        loss = fetched[0] if isinstance(fetched, tuple) else fetched
        if meter is None and log_every:
            # Lazily sized: the first batch fixes the example count per step.
            n = batch_size
            if n is None:
                leaves = [l for l in jax.tree_util.tree_leaves(batch)
                          if getattr(l, "ndim", 0) >= 1]
                n = max((l.shape[0] for l in leaves), default=1)
            meter = ThroughputMeter(batch_size=n, log_every=log_every, log=False)
        if meter is not None:
            # The meter syncs (device->host read of the loss) only at its period
            # boundaries — one boundary per log_every steps, not per step — and
            # excludes its warmup step, so boundaries land at 1 + k*log_every
            # local steps.
            rate = meter.step(sync=loss)
            if rate is not None:
                logging.info("train: step %d loss %.4f %.1f examples/s",
                             step_i + 1, float(loss), rate)
                if on_metrics is not None:
                    on_metrics(step_i + 1, float(loss), rate)
        if (eval_every and (step_i + 1) % eval_every == 0
                and not getattr(runner, "_is_remote_worker", False)):
            # Async remote workers skip: their local state is a compile-shapes
            # template and AsyncPSRunner.evaluate raises there by design. Sync
            # SPMD processes all evaluate together (the compiled eval is a
            # collective program).
            val = runner.evaluate(state, eval_batch, eval_fn)
            try:
                logging.info("train: step %d eval %.6f", step_i + 1, float(val))
            except (TypeError, ValueError):
                logging.info("train: step %d eval (pytree)", step_i + 1)
            if on_eval is not None:
                on_eval(step_i + 1, val)
        if (saver is not None and save_participant and save_every
                and (step_i + 1) % save_every == 0 and step_i + 1 < steps):
            saver.save(state, prefix_base, runner=runner,
                       async_write=async_save)

    if saver is not None and save_participant and int(state.step) > start:
        # Final save stays synchronous: train() returning means the state is
        # durably on disk (save() joins any in-flight periodic write first).
        saver.save(state, prefix_base, runner=runner)
    if saver is not None:
        saver.wait()
    return state
