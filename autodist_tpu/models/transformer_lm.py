"""Decoder-only Transformer language model — the flagship workload.

Fills the role of the reference's lm1b language model (``examples/lm1b/
language_model.py:15-30``: LSTM + 793k-vocab sampled softmax), re-designed for TPU:
a decoder-only Transformer whose matmuls are MXU-shaped, activations in bfloat16
with float32 parameters, optional ``jax.checkpoint`` rematerialization to trade
FLOPs for HBM, and an attention hook so sequence-parallel (ring) attention can swap
in. The embedding table is the sparse-gradient parameter the Parallax strategy
routes to PS (reference routed lm1b's embedding the same way).
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class TransformerLMConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 1024
    dropout: float = 0.0          # deterministic by default (benchmark parity)
    dtype: Any = jnp.bfloat16     # activation/compute dtype (params stay f32)
    remat: bool = False           # jax.checkpoint each block
    attention_impl: str = "dot"   # "dot" | "flash" | "blockwise" | "ring" | "ulysses"
    # Fused pallas head+loss (ops/fused_xent): logits never materialize in HBM.
    # Measured faster than the XLA head in the full step at vocab 32k and it
    # unlocks batch sizes whose logits would OOM; the bench runs with it on.
    fused_head: bool = False
    # Tie input embedding and output projection. Untied matches the reference lm1b
    # model (separate sampled-softmax weights, language_model.py:15-30) and keeps the
    # embedding gather-only, so its gradient is row-sparse and Parallax routes it to
    # PS; tied halves the parameters but makes the embedding gradient dense.
    tied_output: bool = True

    def __post_init__(self):
        if self.attention_impl not in ("dot", "flash", "blockwise", "ring",
                                       "ulysses"):
            raise ValueError(f"Unknown attention_impl {self.attention_impl!r}; "
                             f"valid: 'dot', 'flash', 'blockwise', 'ring', "
                             f"'ulysses'")
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")


def causal_mask(length: int, dtype) -> jax.Array:
    # Static lower-triangular mask; -inf encoded as large negative for bf16 safety.
    mask = jnp.tril(jnp.ones((length, length), dtype=bool))
    return jnp.where(mask, jnp.zeros((), dtype), jnp.full((), -1e9, dtype))


def dot_product_attention(q, k, v, mask, dtype):
    """Plain softmax attention: the baseline the pallas flash kernel replaces.

    ``mask`` is additive and broadcastable to [B, H, Q, K] (a [Q, K] causal mask or
    a [B, 1, 1, K] padding mask both work).
    """
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(depth).astype(dtype)
    scores = scores + mask
    # Softmax in f32 for stability, results back to compute dtype.
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class MultiHeadAttention(nn.Module):
    config: TransformerLMConfig

    @nn.compact
    def __call__(self, x, mask, decode: bool = False, decode_pos=None):
        cfg = self.config
        head_dim = cfg.d_model // cfg.n_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(cfg.n_heads, head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name, use_bias=False)
        q, k, v = dense("query")(x), dense("key")(x), dense("value")(x)

        if decode:
            # Autoregressive KV cache (the flax "cache" collection): keys and
            # values persist at their global positions across apply() calls, so
            # each decode step computes q/k/v for ITS tokens only and attends
            # over everything cached — a [total, total] score matrix never
            # materializes. Static shapes: the cache is max_len long from the
            # first step; masking (not shapes) encodes how much is live.
            # attention_impl is deliberately ignored here — flash/ring pay off
            # on long dense score matrices, which decode never builds.
            batch, chunk = x.shape[0], x.shape[1]
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (batch, cfg.max_len, cfg.n_heads, head_dim),
                               cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (batch, cfg.max_len, cfg.n_heads, head_dim),
                               cfg.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            if decode_pos is not None:
                # Per-row positions (the serving plane's continuous batcher):
                # each batch row is an independent sequence parked at its own
                # write frontier, so the scalar cache_index cannot serve them
                # all. decode_pos [B] is the authority for write index AND
                # mask here (cache_index is left untouched — nothing reads it
                # on this path; the caller owns per-row position bookkeeping).
                idx_vec = decode_pos.astype(jnp.int32)
                row_upd = jax.vmap(
                    lambda cache, new, i: jax.lax.dynamic_update_slice_in_dim(
                        cache, new, i, axis=0))
                ck.value = row_upd(ck.value, k.astype(cfg.dtype), idx_vec)
                cv.value = row_upd(cv.value, v.astype(cfg.dtype), idx_vec)
                # Row b's query (global position idx_vec[b] + i) sees keys
                # [0, idx_vec[b] + i] of ITS OWN row only — rows at other
                # frontiers leave stale/garbage cache beyond their own
                # frontier, which this mask excludes.
                q_pos = idx_vec[:, None] + jnp.arange(chunk)[None, :]
                dec_mask = jnp.where(
                    jnp.arange(cfg.max_len)[None, None, :]
                    <= q_pos[:, :, None],
                    jnp.zeros((), cfg.dtype), jnp.full((), -1e9, cfg.dtype))
                ctx = dot_product_attention(q, ck.value, cv.value,
                                            dec_mask[:, None], cfg.dtype)
            else:
                idx = ci.value
                ck.value = jax.lax.dynamic_update_slice_in_dim(
                    ck.value, k.astype(cfg.dtype), idx, axis=1)
                cv.value = jax.lax.dynamic_update_slice_in_dim(
                    cv.value, v.astype(cfg.dtype), idx, axis=1)
                ci.value = idx + chunk
                # Each query (global position idx + i) sees keys [0, idx + i]:
                # causal within the chunk AND excludes the cache's unwritten
                # tail.
                q_pos = idx + jnp.arange(chunk)
                dec_mask = jnp.where(
                    jnp.arange(cfg.max_len)[None, :] <= q_pos[:, None],
                    jnp.zeros((), cfg.dtype), jnp.full((), -1e9, cfg.dtype))
                ctx = dot_product_attention(q, ck.value, cv.value,
                                            dec_mask[None, None], cfg.dtype)
        elif cfg.attention_impl == "flash":
            from autodist_tpu.ops.flash_attention import flash_attention
            ctx = flash_attention(q, k, v, causal=True)
        elif cfg.attention_impl == "blockwise":
            # Pure-JAX O(L) memory path: the long-context choice on backends
            # where the pallas flash kernel cannot compile (dot materializes
            # the [L, L] score matrices and OOMs at long sequences).
            from autodist_tpu.ops.blockwise_attention import blockwise_attention
            ctx = blockwise_attention(q, k, v, causal=True)
        elif cfg.attention_impl in ("ring", "ulysses"):
            # Valid only inside a shard_map binding the `seq` mesh axis with the
            # sequence dim sharded in ring order — the sequence-parallel path
            # (parallel/sequence.py wraps the whole step accordingly). Causality
            # is handled globally (ring masks by shard offset; ulysses regathers
            # the full sequence), not by the local mask. Parameter init happens
            # outside that context (no bound axis); shapes are all that matter
            # there, so the plain path stands in.
            if self.is_initializing():
                ctx = dot_product_attention(q, k, v, mask, cfg.dtype)
            elif cfg.attention_impl == "ring":
                from autodist_tpu.parallel.ring_attention import ring_attention
                ctx = ring_attention(q, k, v, causal=True)
            else:
                from autodist_tpu.parallel.ulysses import ulysses_attention
                ctx = ulysses_attention(q, k, v, causal=True)
        else:  # "dot" (config validates the value set)
            ctx = dot_product_attention(q, k, v, mask, cfg.dtype)

        return nn.DenseGeneral(features=cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
                               param_dtype=jnp.float32, name="out", use_bias=False)(ctx)


class Block(nn.Module):
    config: TransformerLMConfig

    @nn.compact
    def __call__(self, x, mask, decode: bool = False, decode_pos=None):
        cfg = self.config
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_attn")(x)
        x = x + MultiHeadAttention(cfg, name="attn")(h, mask, decode=decode,
                                                     decode_pos=decode_pos)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_mlp")(x)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_in", use_bias=False)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_out", use_bias=False)(h)
        return x + h


class TransformerLM(nn.Module):
    config: TransformerLMConfig

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_hidden=False,
                 decode: bool = False):
        """``pos_offset``: global position of ``tokens[:, 0]`` — nonzero when this
        call sees one sequence shard (the sequence-parallel path passes the ring
        offset so position embeddings stay globally correct) and during
        autoregressive decoding (the generation loop passes the write position).
        Under ``decode`` it may also be a ``[B]`` int vector giving each batch
        row its OWN position — the serving plane's continuous batcher, where
        every slot is an independent request parked at a different frontier;
        the vector then drives the per-row KV-cache write index and mask too.
        ``return_hidden``: skip the vocab projection and return the final hidden
        states (the fused-head loss owns the projection).
        ``decode``: autoregressive KV-cache mode (run under
        ``mutable=["cache"]``; see :func:`generate`)."""
        cfg = self.config
        _, length = tokens.shape
        emb = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="embed")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_len, cfg.d_model), jnp.float32)
        decode_pos = None
        if jnp.ndim(pos_offset) == 1:
            if not decode:
                raise ValueError("per-row pos_offset requires decode=True "
                                 "(the KV-cache path owns per-row positions)")
            decode_pos = pos_offset
            pos_idx = decode_pos[:, None] + jnp.arange(length)[None, :]
            pos_slice = jnp.take(pos, pos_idx, axis=0)        # [B, L, D]
            x = emb(tokens) + pos_slice.astype(cfg.dtype)
        else:
            pos_slice = jax.lax.dynamic_slice_in_dim(pos, pos_offset, length,
                                                     axis=0)
            x = emb(tokens) + pos_slice[None].astype(cfg.dtype)
        mask = causal_mask(length, cfg.dtype)

        if cfg.remat and not decode:
            # remat trades recompute for activation memory in training; decode
            # steps keep no activations worth trading. The remat'd call must
            # not see the decode kwarg at all: lifted checkpoint would trace
            # the bool into an abstract value and break the Python branch.
            for i in range(cfg.n_layers):
                x = nn.remat(Block, static_argnums=())(
                    cfg, name=f"block_{i}")(x, mask)
        else:
            for i in range(cfg.n_layers):
                x = Block(cfg, name=f"block_{i}")(x, mask, decode=decode,
                                                  decode_pos=decode_pos)

        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        # Head matmul in compute dtype: on TPU an f32 [B*S, d, V] matmul runs at
        # a fraction of the bf16 MXU rate and the head is ~half this model's
        # FLOPs. Softmax stability comes from the f32 upcast in the loss, not
        # from f32 logits.
        if return_hidden:
            # The fused-head loss owns the projection; head params exist from
            # init (which runs the normal path below).
            return x
        if cfg.tied_output:
            return emb.attend(x)
        return nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                        param_dtype=jnp.float32, use_bias=False,
                        name="lm_head")(x)


def fused_head_nll(model: TransformerLM, params, inputs, targets,
                   pos_offset=0) -> jax.Array:
    """Per-token NLL [B, T] through the fused pallas head+loss — shared by
    :func:`make_loss_fn` and the sequence-parallel loss
    (``parallel/sequence.py``). The head-param/layout contract itself lives in
    :func:`autodist_tpu.models.common.fused_lm_head_nll` (one definition for
    the whole zoo)."""
    from autodist_tpu.models.common import fused_lm_head_nll
    h = model.apply({"params": params}, inputs, pos_offset=pos_offset,
                    return_hidden=True)
    return fused_lm_head_nll(h, params, targets, tied=model.config.tied_output)


def make_loss_fn(model: TransformerLM) -> Callable:
    """Next-token cross entropy; batch = {"tokens": int32 [B, L+1]} (inputs/targets
    shifted internally). Matches the reference's lm1b objective shape (words/sec is
    counted over target tokens, lm1b_train.py:64-74)."""

    def xla_nll(params, inputs, targets):
        logits = model.apply({"params": params}, inputs)
        # Xent in f32 whatever the head computed in (bf16 logits are standard;
        # the log-softmax reduction is where precision actually matters).
        logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]

    per_token_nll = (functools.partial(fused_head_nll, model)
                     if model.config.fused_head else xla_nll)

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        nll = per_token_nll(params, inputs, targets)      # [B, T]
        if "mask" in batch:
            mask = batch["mask"][:, 1:].astype(nll.dtype)
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return nll.mean()

    return loss_fn


# Canonical definition in models/common.py (shared with the LSTM family);
# re-exported here because generation on the flagship is this module's API.
from autodist_tpu.models.common import sample_logits  # noqa: E402,F401


def generate(model: TransformerLM, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Autoregressive generation with a KV cache: ``[B, P]`` int32 prompt ->
    ``[B, max_new_tokens]`` sampled continuation.

    TPU-shaped throughout: one full-prompt prefill apply writes the cache
    (position embeddings and causality handled by the decode path), then a
    single ``lax.scan`` of per-token steps — static shapes, no Python loop
    over tokens, the cache donated through the carry. Works under ``jit`` —
    prefer :func:`make_generate_fn`, which closes over every static
    (``model``, ``max_new_tokens``, ``temperature``, ``top_k``, ``top_p``)
    correctly; hand-jitting needs ``static_argnums=(0, 3, 4, 5, 6)`` (all of
    those, ``top_p`` included — a traced ``top_p`` fails the ``if top_p``
    branch at trace time). Sharded/replicated params work as placed — XLA
    inserts any collectives. The reference had no generation path at all (serving =
    SavedModel export); this is the TPU-native inference loop its exported
    models would still need.
    """
    cfg = model.config
    batch, prompt_len = prompt.shape
    if prompt_len < 1:
        raise ValueError("prompt must have at least one token")
    if prompt_len + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_len ({cfg.max_len})")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    # Prefill: the whole prompt in one decode apply (the chunked cache write).
    # return_hidden + a one-position head projection: only the LAST prompt
    # position's logits are needed, so the [B, P, vocab] tensor (and its
    # P-times-larger head matmul) never materializes.
    from autodist_tpu.models.common import lm_head_logits
    hidden, variables = model.apply({"params": params}, prompt, pos_offset=0,
                                    decode=True, return_hidden=True,
                                    mutable=["cache"])
    last = lm_head_logits(hidden[:, -1], params, tied=cfg.tied_output)
    keys = jax.random.split(rng, max_new_tokens)
    first = sample_logits(last, keys[0], temperature, top_k, top_p)

    def step(carry, key):
        cache, tok, pos = carry
        logits, variables = model.apply(
            {"params": params, "cache": cache}, tok[:, None], pos_offset=pos,
            decode=True, mutable=["cache"])
        nxt = sample_logits(logits[:, 0], key, temperature, top_k, top_p)
        return (variables["cache"], nxt, pos + 1), nxt

    if max_new_tokens == 1:
        return first[:, None]
    init = (variables["cache"], first, jnp.asarray(prompt_len, jnp.int32))
    _, rest = jax.lax.scan(step, init, keys[1:])
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def make_generate_fn(model: TransformerLM, max_new_tokens: int,
                     temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 0.0) -> Callable:
    """``jit``-compiled ``f(params, prompt, rng=None) -> [B, max_new_tokens]``
    closing over the statics (one compile per prompt shape)."""
    def f(params, prompt, rng=None):
        return generate(model, params, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, rng=rng)
    return jax.jit(f)


def init_params(config: TransformerLMConfig, rng: Optional[jax.Array] = None,
                batch_size: int = 2):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = TransformerLM(config)
    tokens = jnp.zeros((batch_size, min(8, config.max_len)), jnp.int32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, tokens, rng=rng)


def synthetic_batch(config: TransformerLMConfig, batch_size: int, seq_len: int,
                    seed: int = 0):
    rng = np.random.RandomState(seed)
    return {"tokens": rng.randint(0, config.vocab_size,
                                  size=(batch_size, seq_len + 1)).astype(np.int32)}
