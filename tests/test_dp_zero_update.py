"""ZeRO-style cross-replica weight-update sharding (arXiv 2004.13336).

Covers both regimes behind the ``AUTODIST_ZERO`` / ``zero=`` knob:

- collective path: ``ShardingPlan.with_zero_update`` reshards the optimizer
  state over the data-parallel axes and the jitted step constrains
  grads/updates/params, so XLA lowers the update into reduce-scatter ->
  shard-local update -> all-gather. Pinned here: parity with the unsharded
  update over sgd/momentum/adam, composition with ``unroll=K`` and gradient
  accumulation, and the per-device optimizer-state byte reduction.
- async-PS path: ``ShardedParameterService`` applies each worker's update
  over S concurrent parameter shards on the chief. Pinned here: parity with
  the serial service, per-shard version accounting under the staleness gate,
  the ``ps.apply`` span fan-out, and gather-on-save checkpoints restoring
  across sharded/unsharded topologies.

Named ``test_dp_zero_update`` (not ``test_zero_update``) so it sorts
IN-WINDOW — before ``test_image_data`` — per the tier-1 budget convention
(see test_host_telemetry / test_cluster_trace); pure in-process, no
subprocess.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, telemetry
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.parallel.plan import ParamPlan, ShardingPlan
from autodist_tpu.parallel.staleness import (AsyncPSRunner,
                                             ShardedParameterService,
                                             StalenessTimeout)
from autodist_tpu.strategy import AllReduce, PS

BATCH = 32
D_IN, D_HID, D_OUT = 8, 16, 16


def _loss(p, b):
    h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
    return jnp.mean((b["y"] - h @ p["w2"]) ** 2)


def _params():
    rng = np.random.RandomState(7)
    return {"w1": rng.randn(D_IN, D_HID).astype(np.float32) * 0.3,
            "b1": np.zeros((D_HID,), np.float32),
            "w2": rng.randn(D_HID, D_OUT).astype(np.float32) * 0.3}


def _batch(i):
    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(BATCH, D_IN).astype(np.float32),
            "y": rng.randn(BATCH, D_OUT).astype(np.float32)}


def _session(optimizer, zero, **kw):
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(_loss, _params(), optimizer,
                                         example_batch=_batch(0), zero=zero,
                                         **kw)


def _run_steps(runner, n, start=0):
    state = runner.init(_params())
    for i in range(start, start + n):
        state, loss = runner.run(state, _batch(i))
    return state, loss


def _assert_tree_close(a, b, **tol):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(jax.device_get(x)),
                                   np.asarray(jax.device_get(y)), **tol)


# --------------------------------------------------------- collective path

OPTIMIZERS = {
    "sgd": lambda: optax.sgd(0.05),
    "momentum": lambda: optax.sgd(0.05, momentum=0.9),
    "adam": lambda: optax.adam(1e-2),
}


@pytest.mark.parametrize("opt_name", list(OPTIMIZERS), ids=str)
def test_sharded_update_parity(opt_name):
    """zero=1 must train to the same params AND the same (gathered) optimizer
    state as the replicated update, for every optimizer family the repo
    benches (elementwise transformation chains)."""
    s0, _ = _run_steps(_session(OPTIMIZERS[opt_name](), zero=0), 5)
    s1, _ = _run_steps(_session(OPTIMIZERS[opt_name](), zero=1), 5)
    _assert_tree_close(s0.params, s1.params, rtol=1e-5, atol=1e-6)
    _assert_tree_close(s0.opt_state, s1.opt_state, rtol=1e-5, atol=1e-6)


def test_opt_state_sharded_and_bytes_divided():
    """The moments are PHYSICALLY sharded over the dp axes and the per-device
    footprint drops by ~dp (every leaf of this model tiles evenly)."""
    r0 = _session(optax.adam(1e-2), zero=0)
    r1 = _session(optax.adam(1e-2), zero=1)
    assert r1.plan.zero and not r0.plan.zero
    st0, st1 = r0.init(_params()), r1.init(_params())
    dp = r1.plan.dp_size
    assert dp >= 2
    specs = {str(l.sharding.spec)
             for l in jax.tree_util.tree_leaves(st1.opt_state)
             if hasattr(l, "sharding") and l.ndim}
    assert any("data" in s for s in specs), specs
    b0 = telemetry.opt_state_bytes(st0.opt_state)
    b1 = telemetry.opt_state_bytes(st1.opt_state)
    # Every moment leaf tiles evenly here, so the ratio is ~dp exactly (the
    # scalar step counter stays replicated); 1.5 is the bench gate floor.
    assert b0 / b1 >= max(1.5, dp / 2), (b0, b1, dp)


def test_unroll_composition():
    """run_many (fused K-step scan) under zero=1: same step body, so the
    fused path must match K sequential run() calls exactly, and the
    replicated reference within float tolerance."""
    runner = _session(optax.adam(1e-2), zero=1)
    state_a = runner.init(_params())
    for i in range(4):
        state_a, _ = runner.run(state_a, _batch(i))
    state_b = runner.init(_params())
    state_b, losses = runner.run_many(state_b, [_batch(i) for i in range(4)])
    assert losses.shape == (4,)
    _assert_tree_close(state_a.params, state_b.params, rtol=0, atol=0)
    s_ref, _ = _run_steps(_session(optax.adam(1e-2), zero=0), 4)
    _assert_tree_close(s_ref.params, state_b.params, rtol=1e-5, atol=1e-6)


def test_accumulation_composition():
    """Gradient accumulation's micro-batch scan composes with the sharded
    update: zero=1 parity vs zero=0 at accumulation_steps=2."""
    s0, _ = _run_steps(_session(optax.adam(1e-2), zero=0,
                                accumulation_steps=2), 4)
    s1, _ = _run_steps(_session(optax.adam(1e-2), zero=1,
                                accumulation_steps=2), 4)
    _assert_tree_close(s0.params, s1.params, rtol=1e-5, atol=1e-6)
    _assert_tree_close(s0.opt_state, s1.opt_state, rtol=1e-5, atol=1e-6)


def test_zero_flag_env_default(monkeypatch):
    """zero=None reads AUTODIST_ZERO; the flag is registered (GL007)."""
    from autodist_tpu import const
    assert "AUTODIST_ZERO" in const.KNOWN_FLAGS
    monkeypatch.setenv("AUTODIST_ZERO", "1")
    runner = _session(optax.sgd(0.05), zero=None)
    assert runner.zero == 1 and runner.plan.zero
    monkeypatch.setenv("AUTODIST_ZERO", "0")
    runner = _session(optax.sgd(0.05), zero=None)
    assert runner.zero == 0 and not runner.plan.zero


def test_with_zero_update_plan_rules():
    """Leaves with no evenly-tiling free axis keep their existing opt spec;
    tiling ones gain the dp axes; storage (padded) dims decide."""
    from jax.sharding import PartitionSpec as P
    import collections
    mesh_axes = collections.OrderedDict([("data", 4), ("reduce", 1)])
    params = {
        "even": ParamPlan(name="even", pspec=P(), opt_pspec=P(),
                          sync="allreduce", shape=(8, 3)),
        "odd": ParamPlan(name="odd", pspec=P(), opt_pspec=P(),
                         sync="allreduce", shape=(3, 5)),
        "scalar": ParamPlan(name="scalar", pspec=P(), opt_pspec=P(),
                            sync="allreduce", shape=()),
    }
    plan = ShardingPlan(mesh_axes, params).with_zero_update()
    assert plan.zero
    assert plan.params["even"].opt_pspec == P(("data", "reduce"), None)
    assert plan.params["odd"].opt_pspec == P()      # 3 % 4 and 5 % 4 != 0
    assert plan.params["scalar"].opt_pspec == P()   # nothing to shard


# ------------------------------------------------------------ async-PS path

def _ps_session(zero, optimizer=None, **kw):
    ad = AutoDist(strategy_builder=PS(sync=False))
    return ad.create_distributed_session(
        _loss, _params(), optimizer or optax.adam(1e-2),
        example_batch=_batch(0), zero=zero, **kw)


def test_ps_sharded_apply_parity_and_versions():
    """The S-shard concurrent chief apply lands the same params and the same
    (re-assembled) optimizer state as the serial whole-tree apply, and the
    version plane counts per shard: aggregate version = shards x updates."""
    runs = {}
    for zero in (0, 4):
        runner = _ps_session(zero)
        runner.init(_params())
        w = runner.worker(0)
        for i in range(5):
            w.step(_batch(i), timeout=30)
        runs[zero] = runner
    serial, sharded = runs[0].service, runs[4].service
    assert isinstance(sharded, ShardedParameterService)
    assert not isinstance(serial, ShardedParameterService)
    assert sharded.shards == 3  # one per leaf (clamped from 4)
    assert sharded.shard_versions == [5, 5, 5]
    assert sharded.version == sharded.shards * 5
    assert sharded.updates_applied == 5
    _assert_tree_close(serial.state.params, sharded.state.params,
                       rtol=1e-5, atol=1e-6)
    _assert_tree_close(serial.state.opt_state, sharded.state.opt_state,
                       rtol=1e-5, atol=1e-6)
    assert int(np.asarray(sharded.state.step)) == 5
    runs[4].close()


def test_ps_default_shard_count_and_off():
    """zero=1/True picks the default fan-out (clamped to the leaf count);
    zero=0 keeps the serial service."""
    r = _ps_session(True)
    r.init(_params())
    assert isinstance(r.service, ShardedParameterService)
    assert r.service.shards == 3
    r.close()
    r0 = _ps_session(0)
    r0.init(_params())
    assert not isinstance(r0.service, ShardedParameterService)


def test_ps_apply_span_fanout():
    """Each shard apply emits its own ``ps.apply`` span carrying shard/shards
    args — the cluster-trace view of the concurrent fan-out."""
    runner = _ps_session(4)
    runner.init(_params())
    was = telemetry.enabled()
    telemetry.enable()
    telemetry.clear()
    try:
        runner.worker(0).step(_batch(0), timeout=30)
        spans = [s for s in telemetry.snapshot_spans() if s[0] == "ps.apply"]
        shards = sorted(s[4].get("shard") for s in spans)
        assert shards == [0, 1, 2], spans
        assert all(s[4].get("shards") == 3 for s in spans)
    finally:
        telemetry.clear()
        if not was:
            telemetry.disable()
    runner.close()


def test_ps_staleness_gate_with_sharded_service():
    """The c9 staleness contract is unchanged under the sharded apply: a fast
    worker runs exactly ``staleness`` steps ahead, and the aggregate version
    accounts shards x (all workers' updates)."""
    staleness = 2
    ad = AutoDist(strategy_builder=PS(staleness=staleness))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.05),
                                           example_batch=_batch(0),
                                           num_workers=2, zero=4)
    runner.init(_params())
    fast, slow = runner.worker(0), runner.worker(1)
    for _ in range(staleness):
        fast.step(_batch(0), timeout=30)
    with pytest.raises(StalenessTimeout):
        fast.step(_batch(0), timeout=0.2)
    slow.step(_batch(1), timeout=30)
    fast.step(_batch(0), timeout=30)
    assert runner.service.version == runner.service.shards * (
        fast.steps_completed + slow.steps_completed)
    runner.close()


def test_ps_sharded_restore_reseeds():
    """reset() re-splits a whole-tree state into the per-shard slices: a
    restored checkpoint must be what workers pull next."""
    runner = _ps_session(4)
    state0 = runner.init(_params())
    runner.worker(0).step(_batch(0), timeout=30)
    svc = runner.service
    ckpt = svc.state    # gathered, unsharded structure
    runner.worker(0).step(_batch(1), timeout=30)
    svc.reset(ckpt)
    _assert_tree_close(svc.state.params, ckpt.params, rtol=0, atol=0)
    _assert_tree_close(svc.state.opt_state, ckpt.opt_state, rtol=0, atol=0)
    params0, _, v = svc.read()
    _assert_tree_close(params0, ckpt.params, rtol=0, atol=0)
    runner.close()
    del state0


# ------------------------------------------------------------- checkpoints

def test_checkpoint_cross_restore_both_ways(tmp_path):
    """Gather-on-save: a sharded run's checkpoint holds full logical opt
    moments and restores into an unsharded run (and vice versa), continuing
    to the same params as an uninterrupted reference."""
    ref, _ = _run_steps(_session(optax.adam(1e-2), zero=0), 6)

    # sharded run -> save at 3 -> restore into UNSHARDED run -> 3 more steps
    r1 = _session(optax.adam(1e-2), zero=1)
    st, _ = _run_steps(r1, 3)
    Saver().save(st, str(tmp_path / "m"), global_step=3)
    z = dict(np.load(str(tmp_path / "m-3.npz")))
    assert z["__opt__/0/mu/w1"].shape == (D_IN, D_HID)  # full logical shape
    r0 = _session(optax.adam(1e-2), zero=0)
    st0 = Saver().restore(str(tmp_path / "m-3"), runner=r0)
    for i in range(3, 6):
        st0, _ = r0.run(st0, _batch(i))
    _assert_tree_close(ref.params, st0.params, rtol=1e-5, atol=1e-6)

    # unsharded run -> save at 3 -> restore into SHARDED run -> 3 more steps
    rA = _session(optax.adam(1e-2), zero=0)
    sa, _ = _run_steps(rA, 3)
    Saver().save(sa, str(tmp_path / "n"), global_step=3)
    rB = _session(optax.adam(1e-2), zero=1)
    sb = Saver().restore(str(tmp_path / "n-3"), runner=rB)
    specs = {str(l.sharding.spec)
             for l in jax.tree_util.tree_leaves(sb.opt_state)
             if hasattr(l, "sharding") and l.ndim}
    assert any("data" in s for s in specs), specs  # restored RE-sharded
    for i in range(3, 6):
        sb, _ = rB.run(sb, _batch(i))
    _assert_tree_close(ref.params, sb.params, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------- telemetry

def test_opt_state_bytes_gauge():
    """sample_device_memory(opt_state=...) books the train.opt_state_bytes
    gauge — the number the ZeRO bench divides."""
    runner = _session(optax.adam(1e-2), zero=1)
    state = runner.init(_params())
    was = telemetry.enabled()
    telemetry.enable()
    try:
        wrote = telemetry.sample_device_memory(opt_state=state.opt_state)
        assert wrote >= 1
        got = telemetry.registry().snapshot()["train.opt_state_bytes"]
        assert got == telemetry.opt_state_bytes(state.opt_state) > 0
    finally:
        telemetry.clear()
        if not was:
            telemetry.disable()
