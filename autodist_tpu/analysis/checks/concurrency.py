"""Concurrency checks: GL001 lock-across-dispatch, GL002 lock order, GL005
unbounded blocking.

These descend from real bugs in this repo's history: PR 2 shipped a
machine-dependent deadlock where concurrently dispatched multi-device XLA
programs interleaved their collective rendezvous (fixed by
``AsyncPSRunner._collective_lock``), and ``staleness.ParameterService``
documents a strict ``_write_mutex -> _lock`` order plus a "device execution
never runs under the snapshot lock" rule that nothing previously enforced.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register

_LOCK_TOKENS = {"lock", "rlock", "mutex", "mtx", "cond", "condition",
                "sem", "semaphore"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_DISPATCH_ATTRS = {"block_until_ready", "device_put", "device_get",
                   "sendall", "sendmsg", "sendto", "recv", "recv_into",
                   "recvfrom", "recvmsg", "connect", "accept"}
_DISPATCH_METHODS = {"run", "run_many"}


def _definite_locks(tree: ast.Module) -> Set[str]:
    """Dotted targets assigned a ``threading.Lock()``-family constructor."""
    locks: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        ctor = callgraph.last_attr(node.value.func)
        if ctor not in _LOCK_CTORS:
            continue
        for target in node.targets:
            name = callgraph.dotted_name(target)
            if name:
                locks.add(name)
    return locks


def _lock_name(expr, definite: Set[str]) -> Optional[str]:
    """The lock's short name when ``expr`` looks like a lock, else None.
    Either the expression was assigned a threading constructor in this module,
    or its final identifier carries a lock-ish token (``_collective_lock``,
    ``_write_mutex``, ``_cond`` — token match, so "block" never trips)."""
    dotted = callgraph.dotted_name(expr)
    last = callgraph.last_attr(expr)
    if dotted is not None and dotted in definite:
        return last or dotted
    if callgraph.name_tokens(last) & _LOCK_TOKENS:
        return last
    return None


def _jitted_names(tree: ast.Module) -> Set[str]:
    """Dotted targets assigned from a ``jax.jit(...)``/``jit(...)`` call."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) \
                or not isinstance(node.value, ast.Call):
            continue
        fn = callgraph.dotted_name(node.value.func) or ""
        if fn == "jit" or fn.endswith(".jit"):
            for target in node.targets:
                name = callgraph.dotted_name(target)
                if name:
                    names.add(name)
    return names


def _enclosing_class(module: Module, index: callgraph.ModuleIndex,
                     node) -> Optional[str]:
    """Class name owning ``node``'s enclosing method, for self-call resolution."""
    scope = module.scope_at(node)
    head = scope.split(".")[0] if scope else ""
    if any(cls == head for cls, _ in index.methods):
        return head
    return None


@register("GL001", "lock held across device dispatch / blocking I/O")
def check_lock_across_dispatch(module: Module,
                               ctx: Context) -> List[Finding]:
    """GL001 — lock-held-across-dispatch.

    Flags a ``with <lock>:`` body that reaches (directly or through
    same-module helpers, up to 5 hops) a blocking operation: a jit-compiled
    callable, ``runner.run``/``run_many``, ``jax.block_until_ready``, or
    socket send/recv. Holding a lock across multi-device XLA execution can
    wedge the collective rendezvous — the PR 2 deadlock, which hung the whole
    tier-1 suite 3/3 on a 2-core box — and holding a hot-path snapshot lock
    across device execution stalls every reader for a whole program
    (the ``staleness.ParameterService`` rule: the apply's device execution
    runs under the writer mutex only, never the snapshot Condition).

    Locks that exist precisely to serialize execution (e.g.
    ``AsyncPSRunner._collective_lock``) are legitimate; annotate those sites
    with ``# graftlint: disable=GL001(reason)`` so the intent is explicit and
    reviewed, instead of implicit and forgettable.
    """
    if module.tree is None:
        return []
    findings: List[Finding] = []
    definite = _definite_locks(module.tree)
    jitted = _jitted_names(module.tree)
    index = callgraph.ModuleIndex(module.tree)

    def predicate(call: ast.Call) -> Optional[str]:
        dotted = callgraph.dotted_name(call.func)
        last = callgraph.last_attr(call.func)
        if last in _DISPATCH_ATTRS:
            return dotted or last
        if last in _DISPATCH_METHODS and isinstance(call.func, ast.Attribute):
            return dotted or last
        if dotted is not None and dotted in jitted:
            return f"{dotted} (jitted)"
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            lock = _lock_name(item.context_expr, definite)
            if lock is None:
                continue
            cls = _enclosing_class(module, index, node)
            hit = callgraph.find_reaching_call(
                index, list(node.body), cls, predicate)
            if hit is None:
                continue
            _, label, path = hit
            via = " via " + " -> ".join(path[:-1]) if len(path) > 1 else ""
            findings.append(Finding(
                "GL001", module.relpath, node.lineno, node.col_offset,
                f"lock `{lock}` is held across blocking call `{label}`{via}; "
                f"dispatching device programs or socket I/O inside a critical "
                f"section risks deadlocking the collective rendezvous "
                f"(PR 2) and stalls every other thread on the lock",
                scope=module.scope_at(node)))
            break  # one finding per with-statement is enough signal
    return findings


def _nested_lock_edges(module: Module, index: callgraph.ModuleIndex,
                       definite: Set[str]):
    """(outer, inner, node) lock-acquisition edges: direct ``with`` nesting
    plus one level of same-module call resolution."""
    edges = []
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        outers = [_lock_name(i.context_expr, definite) for i in node.items]
        outers = [o for o in outers if o]
        if not outers:
            continue
        cls = _enclosing_class(module, index, node)
        # walk_executed: a `with B:` inside a def merely DEFINED under A is
        # deferred code — not an A->B acquisition.
        inner_withs = [sub for body in node.body
                       for sub in callgraph.walk_executed(body)
                       if isinstance(sub, (ast.With, ast.AsyncWith))]
        for call in (c for body in node.body
                     for c in callgraph.calls_executed(body)):
            target = index.resolve(call, cls)
            if target is not None:
                inner_withs.extend(
                    sub for stmt in target.body
                    for sub in callgraph.walk_executed(stmt)
                    if isinstance(sub, (ast.With, ast.AsyncWith)))
        for sub in inner_withs:
            for item in sub.items:
                inner = _lock_name(item.context_expr, definite)
                if inner is None:
                    continue
                for outer in outers:
                    if outer != inner:
                        edges.append((outer, inner, sub))
    return edges


@register("GL002", "lock-order inversion / undeclared nesting")
def check_lock_order(module: Module, ctx: Context) -> List[Finding]:
    """GL002 — lock-order inversion.

    Derives the acquisition order of named locks (direct ``with`` nesting
    plus one level of same-module calls) and flags (a) any pair acquired in
    both orders anywhere in the module — a classic ABBA deadlock — and
    (b) any nested acquisition not covered by a declared order directive.
    Declare the module's intended order once, next to the lock definitions:

        # graftlint: lock-order=_write_mutex->_lock

    The directive is the machine-readable version of the prose rule
    ``staleness.ParameterService`` always had ("Order: _write_mutex ->
    _lock, never the reverse"); with it declared, a future path acquiring
    ``_lock`` then ``_write_mutex`` fails lint instead of deadlocking a
    production chief under load.
    """
    if module.tree is None:
        return []
    findings: List[Finding] = []
    definite = _definite_locks(module.tree)
    index = callgraph.ModuleIndex(module.tree)
    declared = set(module.lock_orders)
    seen: Dict[Tuple[str, str], ast.AST] = {}
    reported: Set[Tuple[str, str, str]] = set()

    for outer, inner, node in _nested_lock_edges(module, index, definite):
        scope = module.scope_at(node)
        if (outer, inner, scope) in reported:
            continue
        reported.add((outer, inner, scope))
        if (inner, outer) in seen or (inner, outer) in declared:
            findings.append(Finding(
                "GL002", module.relpath, node.lineno, node.col_offset,
                f"acquires `{inner}` while holding `{outer}`, conflicting "
                f"with the established order `{inner}` -> `{outer}`; "
                f"two threads taking these locks in opposite orders "
                f"deadlock each other",
                scope=scope))
        elif (outer, inner) not in declared:
            findings.append(Finding(
                "GL002", module.relpath, node.lineno, node.col_offset,
                f"nested lock acquisition `{outer}` -> `{inner}` has no "
                f"declared order; add `# graftlint: "
                f"lock-order={outer}->{inner}` at module level so future "
                f"paths cannot silently invert it",
                scope=scope))
        seen.setdefault((outer, inner), node)
    return findings


@register("GL005", "unbounded blocking wait in runtime code")
def check_unbounded_wait(module: Module, ctx: Context) -> List[Finding]:
    """GL005 — blocking call without a timeout path.

    In ``autodist_tpu/`` runtime code (handlers the PS transport runs per
    connection, gate waits, prefetch joins), flags ``Condition.wait`` /
    ``wait_for`` / ``Event.wait`` calls with no timeout argument (or a
    literal ``None``): a dead peer or wedged producer then parks the thread
    forever with no diagnosable failure. The PS server bounds the
    wait-indefinitely gate default for the same reason
    (``ps_transport._dispatch``: client-requested finite timeouts are
    honored exactly; ``None`` gets a 24h ceiling so a vanished peer cannot
    park handler threads forever). Tests and tools are exempt (a test
    hanging is loud; a server thread leaking is silent).
    """
    if module.tree is None or not module.relpath.startswith("autodist_tpu/"):
        return []
    findings: List[Finding] = []
    for call in callgraph.calls_under(module.tree):
        last = callgraph.last_attr(call.func)
        if last not in ("wait", "wait_for"):
            continue
        if last == "wait":
            receiver = call.func.value if isinstance(call.func, ast.Attribute) \
                else None
            tokens = callgraph.name_tokens(callgraph.last_attr(receiver))
            if not tokens & (_LOCK_TOKENS | {"event", "ev", "done", "ready"}):
                continue  # p.wait() on a process etc. — not a lock primitive
            has_timeout = bool(call.args) or any(
                k.arg == "timeout" for k in call.keywords)
            timeout_arg = call.args[0] if call.args else next(
                (k.value for k in call.keywords if k.arg == "timeout"), None)
        else:
            has_timeout = len(call.args) >= 2 or any(
                k.arg == "timeout" for k in call.keywords)
            timeout_arg = call.args[1] if len(call.args) >= 2 else next(
                (k.value for k in call.keywords if k.arg == "timeout"), None)
        if has_timeout and not (isinstance(timeout_arg, ast.Constant)
                                and timeout_arg.value is None):
            continue
        dotted = callgraph.dotted_name(call.func) or last
        findings.append(Finding(
            "GL005", module.relpath, call.lineno, call.col_offset,
            f"unbounded `{dotted}` — no timeout, so a dead peer or wedged "
            f"producer parks this thread forever; pass a timeout and handle "
            f"expiry (see StalenessController.start_step)",
            scope=module.scope_at(call)))
    return findings
