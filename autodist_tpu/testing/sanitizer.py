"""graftsan: the runtime half of the concurrency plane.

graftlint's static checks (GL001/GL002/GL005, whole-program since the
interprocedural lift) reason about lock orderings they can *prove* from
source; they cannot see orderings that only materialize through dynamic
dispatch, fault-injected paths, or the threads a test actually spawns. This
module closes that blind spot the standard sanitizer way: observe the real
execution, check it online, and export what was seen so the static model can
be cross-checked (``graftlint --crosscheck``).

Arming (``AUTODIST_SANITIZE``, comma-set — read once at import through the
typed ``const.ENV`` registry):

``locks``
    Every primitive built through the :func:`san_lock` / :func:`san_rlock` /
    :func:`san_condition` factories feeds a process-global lock-order graph
    keyed by creation site ``(relpath, assigned name, owning class)`` — the
    same identity GL002 derives statically, so the two graphs merge. Each
    thread keeps its acquisition stack; acquiring B while holding A adds the
    edge A→B *before* blocking on B, and an edge that closes a cycle raises
    :class:`SanViolation` immediately with BOTH full stacks (this thread's,
    and the recorded stack of the first thread that took the reverse order)
    — a dynamic ABBA aborts the test instead of deadlocking it. Recursive
    acquire of a non-reentrant lock (self-deadlock) is caught the same way.
``waits``
    GL005's runtime twin: ``Condition.wait()`` / ``Event.wait()`` without a
    timeout is a violation (the static check only sees literal call sites —
    this one sees every call, through any number of wrappers), as is
    entering any wait while holding a *different* sanitized lock (the
    lost-wakeup/convoy shape). ``Queue``-style waits are covered wherever
    the queue's internal Condition came from :func:`san_condition` (the
    input-plane ``BoundedQueue`` does).
``threads``
    A pytest fixture fence (:func:`thread_fence`, installed autouse in
    ``tests/conftest.py``): a test that leaks a live non-daemon thread past
    teardown fails with the leaked threads' names and current stacks — the
    leak class GL010 catches for closeables, extended to threads.

Disarmed (the default), the factories return **bare threading primitives**:
the hot-path cost of adoption is one module-global set check at *creation*
time and exactly zero per acquire/release. Product modules therefore adopt
the factories unconditionally.

Export: the observed edge set lands in
``.graftlint_cache/observed_locks.jsonl`` (one JSON object per edge, plus a
``meta`` header line) at process exit when ``locks`` is armed, or on demand
via :func:`dump_observed`. ``tools/graftlint.py --crosscheck`` merges these
edges into GL002's static graph: cycles the static analysis could not reach
become findings, and static edges never observed are reported as
unexercised (coverage for the lock model itself).

Import discipline: this module imports only the stdlib and ``const`` at
module level (it is imported by the lowest-level lock owners — telemetry,
data, parallel — so it must sit below all of them); telemetry metric
booking (``san.violations`` counter, ``san.locks_tracked`` gauge) is lazy
and best-effort. Internal state is guarded by a *bare* lock — the
sanitizer does not sanitize itself.
"""

import atexit
import contextlib
import json
import linecache
import os
import re
import sys
import threading
import time
import traceback

from autodist_tpu import const

__all__ = [
    "SanViolation", "san_lock", "san_rlock", "san_condition", "san_event",
    "modes", "arm", "armed", "reset", "violations", "observed_edges",
    "dump_observed", "thread_fence", "OBSERVED_BASENAME",
]

# Repo root (…/autodist_tpu/testing/sanitizer.py → three dirnames up): keys
# are repo-relative so they line up with graftlint's Module.relpath identity.
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OBSERVED_BASENAME = "observed_locks.jsonl"

_ASSIGN_RE = re.compile(r"\s*([A-Za-z_][\w.]*)\s*(?::[^=]+)?=")


class SanViolation(AssertionError):
    """A concurrency-sanitizer finding: lock-order cycle, unbounded or
    lock-holding wait, or a leaked non-daemon thread. Subclasses
    AssertionError so an armed test run fails loudly under plain pytest."""


def _parse(spec) -> frozenset:
    return frozenset(m.strip() for m in str(spec or "").split(",") if m.strip())


_MODES = _parse(const.ENV.AUTODIST_SANITIZE.val)

# ---------------------------------------------------------------- state
# All bare primitives: the sanitizer's own state is not sanitized.
_STATE_LOCK = threading.Lock()
_EDGES = {}        # (outer_key, inner_key) -> {count, thread, outer_stack, inner_stack}
_ADJ = {}          # outer_key -> set(inner_key)
_KEYS = set()      # every site key ever registered
_VIOLATIONS = []   # [{kind, message}] — grows on every violation raised
_TLS = threading.local()


def _held():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []   # entries: [obj_id, key, count, stack_str]
    return st


def modes() -> frozenset:
    """The armed mode set (empty when disarmed)."""
    return _MODES


def arm(spec) -> str:
    """Set the armed modes from a comma-spec (tests; production arms via the
    ``AUTODIST_SANITIZE`` env flag before import). Returns the previous spec
    so callers can restore it. Already-built primitives keep the armed-ness
    they were created with."""
    global _MODES
    prev = ",".join(sorted(_MODES))
    _MODES = _parse(spec)
    return prev


@contextlib.contextmanager
def armed(spec):
    """Context manager: arm ``spec`` for the body, then restore the previous
    modes and clear the sanitizer's graph/violation state."""
    prev = arm(spec)
    try:
        yield
    finally:
        arm(prev)
        reset()


def reset():
    """Drop the lock-order graph, key registry and violation log (test
    isolation). Primitives already built stay usable; their next acquire
    re-registers their edges."""
    with _STATE_LOCK:
        _EDGES.clear()
        _ADJ.clear()
        _KEYS.clear()
        del _VIOLATIONS[:]


def violations():
    """Snapshot of every violation raised so far in this process."""
    with _STATE_LOCK:
        return list(_VIOLATIONS)


# ------------------------------------------------------------- violations

@contextlib.contextmanager
def _bypass():
    """Mark this thread as inside the sanitizer's own plumbing: wrapped
    primitives it touches (telemetry instrument locks book metrics through
    san_lock too) pass straight through, untracked. Without this, booking
    `san.locks_tracked` while the creating thread holds the telemetry
    registry's own sanitized lock is a REAL recursive acquire — the
    sanitizer deadlocking itself trying to report on itself."""
    prev = getattr(_TLS, "bypass", False)
    _TLS.bypass = True
    try:
        yield
    finally:
        _TLS.bypass = prev


def _bypassed() -> bool:
    return getattr(_TLS, "bypass", False)


def _violate(kind: str, message: str):
    with _STATE_LOCK:
        _VIOLATIONS.append({"kind": kind, "message": message})
    try:  # metric booking is best-effort: telemetry must never mask the raise
        from autodist_tpu.telemetry import metrics as _metrics
        with _bypass():
            _metrics.counter("san.violations").inc()
    except Exception:
        pass
    raise SanViolation(f"graftsan[{kind}]: {message}")


def _register_key(key):
    # No telemetry here: creation often happens under the creator's own
    # locks (a Registry building an instrument), and booking a gauge takes
    # sanitized locks of its own. The gauge is set at export time instead.
    with _STATE_LOCK:
        _KEYS.add(key)


def _site_key(explicit_name, depth=2):
    """Identity of the primitive being created: (repo-relative path of the
    creating module, assigned name parsed from the creation line, owning
    class when created inside a method). Matches the (relpath, name)
    identity GL002 gives the same lock statically; the class qualifier
    disambiguates same-named ``self._lock`` attrs within a module."""
    f = sys._getframe(depth)
    path = f.f_code.co_filename
    rel = os.path.basename(path)
    try:
        cand = os.path.relpath(path, _ROOT)
        if not cand.startswith(".."):
            rel = cand.replace(os.sep, "/")
    except ValueError:
        pass
    slf = f.f_locals.get("self")
    cls = type(slf).__name__ if slf is not None else ""
    name = explicit_name
    if not name:
        m = _ASSIGN_RE.match(linecache.getline(path, f.f_lineno) or "")
        name = m.group(1) if m else f"<{rel}:{f.f_lineno}>"
    key = (rel, name, cls)
    _register_key(key)
    return key


def _acq_stack():
    # Two internal frames (format_stack caller + wrapper method) trimmed.
    return "".join(traceback.format_stack(sys._getframe(2)))


def _find_path(src, dst):
    """DFS src→dst over the edge graph; returns the key path or None."""
    stack, seen = [(src, (src,))], {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _ADJ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _key_str(key):
    rel, name, cls = key
    return f"{rel}:{cls + '.' if cls else ''}{name}"


def _note_acquire(obj_id, key, reentrant, stack, hard=True):
    """Pre-acquire bookkeeping: record edges from every held lock to this
    one, detect order cycles BEFORE blocking (a would-be deadlock raises
    instead of hanging), then push the held entry on success (the caller
    pushes after the real acquire). ``hard`` is False for try-acquires and
    timeout acquires — those cannot self-deadlock (they return), so only
    the order edges are recorded for them."""
    if _bypassed():
        return None
    st = _held()
    for ent in st:
        if ent[0] == obj_id:
            if not reentrant and hard:
                _violate(
                    "locks",
                    f"recursive acquire of non-reentrant lock "
                    f"{_key_str(key)} (self-deadlock)\n"
                    f"--- first acquired at ---\n{ent[3]}"
                    f"--- re-acquired at ---\n{stack}")
            ent[2] += 1
            return None
    cycle_msg = None
    with _STATE_LOCK:
        for ent in st:
            okey = ent[1]
            if okey == key:
                continue  # sibling from the same creation site (lock arrays)
            edge = _EDGES.get((okey, key))
            if edge is not None:
                edge["count"] += 1
                continue
            path = _find_path(key, okey) if "locks" in _MODES else None
            _EDGES[(okey, key)] = {
                "count": 1,
                "thread": threading.current_thread().name,
                "outer_stack": ent[3],
                "inner_stack": stack,
            }
            _ADJ.setdefault(okey, set()).add(key)
            if path is not None and cycle_msg is None:
                rev = _EDGES.get((path[0], path[1]))
                cycle_msg = (
                    f"lock-order cycle: acquiring {_key_str(key)} while "
                    f"holding {_key_str(okey)}, but the reverse order "
                    f"{' -> '.join(_key_str(k) for k in path)} was already "
                    f"observed"
                    + (f" on thread '{rev['thread']}'" if rev else "") + "\n"
                    f"--- this thread: {_key_str(okey)} acquired at ---\n"
                    f"{ent[3]}"
                    f"--- this thread: {_key_str(key)} being acquired at ---\n"
                    f"{stack}"
                    + (f"--- other thread: {_key_str(path[0])} held at ---\n"
                       f"{rev['outer_stack']}"
                       f"--- other thread: {_key_str(path[1])} acquired at "
                       f"---\n{rev['inner_stack']}" if rev else ""))
    if cycle_msg is not None:
        _violate("locks", cycle_msg)
    return [obj_id, key, 1, stack]


def _push_entry(entry):
    if entry is not None:
        _held().append(entry)


def _note_release(obj_id):
    if _bypassed():
        return
    st = _held()
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == obj_id:
            st[i][2] -= 1
            if st[i][2] <= 0:
                del st[i]
            return
    # Acquired before arming, or released by another thread: not an error.


def _pop_entry(obj_id):
    st = _held()
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == obj_id:
            return st.pop(i)
    return None


def _check_wait_holding(obj_id, what):
    if _bypassed():
        return
    for ent in _held():
        if ent[0] != obj_id:
            _violate(
                "waits",
                f"{what} entered while holding sanitized lock "
                f"{_key_str(ent[1])} (acquired at)\n{ent[3]}")


# -------------------------------------------------------------- wrappers

class _SanLockBase:
    """Shared acquire/release/context plumbing over a real primitive."""

    _reentrant = False

    def __init__(self, inner, key):
        self._inner = inner
        self.key = key

    def acquire(self, blocking=True, timeout=-1):
        entry = _note_acquire(id(self), self.key, self._reentrant,
                              _acq_stack(),
                              hard=blocking and (timeout is None
                                                 or timeout < 0))
        got = self._inner.acquire(blocking, timeout)
        if got:
            _push_entry(entry)
        elif entry is None:
            # a held lock's count was bumped optimistically (entry None =
            # already on the stack, or bypassed — release no-ops there);
            # a failed try/timeout acquire must undo it
            _note_release(id(self))
        return got

    def release(self):
        _note_release(id(self))
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __repr__(self):
        return f"<{type(self).__name__} {_key_str(self.key)} {self._inner!r}>"


class _SanLock(_SanLockBase):
    pass


class _SanRLock(_SanLockBase):
    _reentrant = True

    def locked(self):  # RLock has no locked() before 3.12; mirror _is_owned
        return self._inner._is_owned()


class _SanCondition(_SanLockBase):
    """Condition wrapper: the condition IS its lock for ordering purposes
    (acquiring the condition acquires the underlying mutex); ``wait``
    temporarily retires the held entry — the real wait releases the mutex —
    and the ``waits`` mode checks fire before blocking."""

    def __init__(self, inner, key):
        super().__init__(inner, key)

    def _pre_wait(self, timeout, what):
        if "waits" in _MODES:
            if timeout is None:
                _violate("waits",
                         f"{what} on {_key_str(self.key)} without a timeout "
                         f"(unbounded wait)\n{_acq_stack()}")
            _check_wait_holding(id(self), f"{what} on {_key_str(self.key)}")

    def wait(self, timeout=None):
        self._pre_wait(timeout, "Condition.wait")
        entry = _pop_entry(id(self))
        try:
            return self._inner.wait(timeout)
        finally:
            _push_entry(entry)

    def wait_for(self, predicate, timeout=None):
        self._pre_wait(timeout, "Condition.wait_for")
        entry = _pop_entry(id(self))
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _push_entry(entry)

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()

    def locked(self):
        raise AttributeError("Condition has no locked()")


class _SanEvent:
    """Event wrapper: only the ``waits`` checks — events carry no mutual
    exclusion, so they never enter the lock-order graph."""

    def __init__(self, inner, key):
        self._inner = inner
        self.key = key

    def wait(self, timeout=None):
        if "waits" in _MODES:
            if timeout is None:
                _violate("waits",
                         f"Event.wait on {_key_str(self.key)} without a "
                         f"timeout (unbounded wait)\n{_acq_stack()}")
            _check_wait_holding(None, f"Event.wait on {_key_str(self.key)}")
        return self._inner.wait(timeout)

    def set(self):
        self._inner.set()

    def clear(self):
        self._inner.clear()

    def is_set(self):
        return self._inner.is_set()

    def __repr__(self):
        return f"<_SanEvent {_key_str(self.key)} {self._inner!r}>"


def _tracking() -> bool:
    return bool(_MODES & {"locks", "waits"})


# -------------------------------------------------------------- factories

def san_lock(name=None):
    """``threading.Lock()`` — wrapped for order/wait tracking when armed,
    the bare primitive otherwise."""
    if not _tracking():
        return threading.Lock()
    return _SanLock(threading.Lock(), _site_key(name))


def san_rlock(name=None):
    """``threading.RLock()`` with the same arming contract."""
    if not _tracking():
        return threading.RLock()
    return _SanRLock(threading.RLock(), _site_key(name))


def san_condition(lock=None, name=None):
    """``threading.Condition(lock)``. A sanitized lock argument is unwrapped
    for the real condition and lends the condition its identity (they are
    the same mutex)."""
    if not _tracking():
        if isinstance(lock, _SanLockBase):
            lock = lock._inner
        return threading.Condition(lock)
    if isinstance(lock, _SanLockBase):
        return _SanCondition(threading.Condition(lock._inner), lock.key)
    return _SanCondition(threading.Condition(lock), _site_key(name))


def san_event(name=None):
    """``threading.Event()``; wrapped only for the ``waits`` checks."""
    if "waits" not in _MODES:
        return threading.Event()
    return _SanEvent(threading.Event(), _site_key(name))


# ----------------------------------------------------------- thread fence

@contextlib.contextmanager
def thread_fence(grace_s=1.0):
    """Fail the body if it leaks a live NON-DAEMON thread: snapshot the
    thread set, run the body, allow a short grace for orderly teardown,
    then raise :class:`SanViolation` naming every survivor with its current
    stack. Installed autouse per-test by ``tests/conftest.py`` when the
    ``threads`` mode is armed."""
    before = {t.ident for t in threading.enumerate()}
    yield
    deadline = time.monotonic() + grace_s
    while True:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and not t.daemon
                  and t.ident not in before
                  and t is not threading.current_thread()]
        if not leaked or time.monotonic() >= deadline:
            break
        time.sleep(0.02)
    if leaked:
        frames = sys._current_frames()
        lines = []
        for t in leaked:
            lines.append(f"  leaked non-daemon thread '{t.name}' "
                         f"(ident={t.ident}), currently at:")
            frame = frames.get(t.ident)
            lines.append("".join(traceback.format_stack(frame)) if frame
                         else "    <no frame: thread exiting>\n")
        _violate("threads",
                 "test leaked %d non-daemon thread(s) past teardown:\n%s"
                 % (len(leaked), "".join(lines)))


# ----------------------------------------------------------------- export

def observed_edges():
    """The lock-order edges observed so far, as JSON-ready records — the
    same shape :func:`dump_observed` writes and ``--crosscheck`` reads."""
    def as_obj(key):
        return {"path": key[0], "name": key[1], "cls": key[2]}
    with _STATE_LOCK:
        return [{"outer": as_obj(o), "inner": as_obj(i), "count": e["count"]}
                for (o, i), e in sorted(_EDGES.items())]


def dump_observed(path=None):
    """Append the observed edge set (plus a ``meta`` header line, so the
    artifact is non-empty even for an edge-free run) to
    ``<cwd>/.graftlint_cache/observed_locks.jsonl`` or ``path``. Registered
    atexit when ``locks`` is armed; idempotent and safe to call directly."""
    if path is None:
        path = os.path.join(os.getcwd(), ".graftlint_cache", OBSERVED_BASENAME)
    edges = observed_edges()
    with _STATE_LOCK:
        meta = {"meta": {"modes": sorted(_MODES), "locks_tracked": len(_KEYS),
                         "edges": len(edges),
                         "violations": len(_VIOLATIONS)}}
    try:  # gauge booked at export time, never at creation time: creation
        # often runs under the creator's own (sanitized) locks
        from autodist_tpu.telemetry import metrics as _metrics
        with _bypass():
            _metrics.gauge("san.locks_tracked").set(
                meta["meta"]["locks_tracked"])
    except Exception:
        pass
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(meta) + "\n")
            for rec in edges:
                fh.write(json.dumps(rec) + "\n")
    except OSError:
        return None  # read-only checkout: a lost artifact, not a crash
    return path


if "locks" in _MODES:  # production arming is env-driven and import-time
    atexit.register(dump_observed)
