"""Flagship benchmark: Transformer LM training throughput on the active platform.

Reproduces the reference's own measurement procedure (BASELINE.md): the lm1b
words/sec hook (``examples/lm1b/lm1b_train.py:64-74`` printed wps per 100 steps)
re-targeted at the flagship Transformer LM. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "tokens/s", "vs_baseline": N}

The reference publishes no numeric table (figures only), so ``vs_baseline``
normalizes against the BASELINE.md procedural target: V100-class per-device lm1b
throughput, taken as 20k words/sec/device (the upper end of published LSTM-lm1b
single-V100 numbers; the north star is per-chip >= that).
"""

import argparse
import json
import math
import time

import numpy as np
from autodist_tpu.testing.sanitizer import san_lock

BASELINE_TOKENS_PER_SEC_PER_DEVICE = 20_000.0


def _baseline_path():
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PERF_BASELINE.json")


def _append_trajectory(row: dict):
    """Append one perf-history row to BENCH_TRAJECTORY.jsonl (repo root).

    The BENCH_rNN.json artifacts are per-round snapshots that OVERWRITE each
    other's story; this file is the append-only trajectory — one JSON line
    per bench invocation (wall time, metric, rate, MFU, attribution shares
    when the run measured them) so regressions are visible as a series, not
    a pair. A write failure never breaks the bench (read-only checkouts run
    it too)."""
    import os
    row = dict(row, t=time.strftime("%Y-%m-%dT%H:%M:%S"))
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_TRAJECTORY.jsonl")
    try:
        with open(path, "a") as f:
            f.write(json.dumps(row, default=str) + "\n")
    except OSError:
        pass


def legacy_wire_send(sock, obj):
    """The pre-zero-copy transport send, verbatim: full encode to one bytes
    object, header CONCAT, one sendall. The reference implementation of
    'legacy framing' shared by :func:`wire_bench` and the interop tests
    (tests/test_codec_wire.py) so both always pin the same definition."""
    import struct

    from autodist_tpu.parallel import wire
    payload = wire.encode(obj)
    sock.sendall(struct.Struct("!Q").pack(len(payload)) + payload)


def legacy_wire_recv(sock):
    """The pre-zero-copy transport receive, verbatim: chunked accumulate into
    a bytearray, full-copy decode."""
    import struct

    from autodist_tpu.parallel import wire
    hdr = struct.Struct("!Q")

    def read_exact(n):
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf.extend(chunk)
        return bytes(buf)

    (n,) = hdr.unpack(read_exact(hdr.size))
    return wire.decode(read_exact(n))


def wire_bench(payload_mib: int = 40, rounds: int = 4):
    """PS-transport codec/framing micro-bench: round-trip a dense >=32 MiB
    parameter-style pytree over a loopback socketpair through (a) the legacy
    copying path — ``wire.encode`` + header-concat ``sendall`` + chunked
    accumulate receive + ``wire.decode(copy=True)`` — and (b) the zero-copy
    path the transport now ships: ``encode_parts`` borrowed buffers over
    ``sendmsg``, ``recv_into`` a recycled buffer, alias decode. Prints ONE
    JSON line with both throughputs and the speedup, diffed against the
    recorded ``ps_wire`` row in PERF_BASELINE.json. Pure host/CPU work (no
    accelerator): it isolates exactly the wire cost the async-PS data plane
    pays per step."""
    import socket
    import sys
    import threading

    from autodist_tpu.parallel import ps_transport as tp
    from autodist_tpu.parallel import wire

    rng = np.random.RandomState(0)
    n_layers = max(1, payload_mib // 4)
    tree = ("ok", {f"layer{i}": {"w": rng.randn(1024, 1024).astype(np.float32),
                                 "b": rng.randn(1024).astype(np.float32)}
                   for i in range(n_layers)}, None, 7)
    tree_bytes = sum(a.nbytes for lyr in tree[1].values() for a in lyr.values())

    legacy_send, legacy_recv = legacy_wire_send, legacy_wire_recv

    def zc_send(sock, obj):
        tp._send_payload(sock, wire.encode_parts(obj))

    def make_zc_recv():
        pool = tp._RecvBuffer()
        return lambda sock: tp._recv_msg(sock, pool=pool)[0]

    def measure(send_fn, recv_fn_factory):
        a, b = socket.socketpair()
        stop = []

        def echo():  # decode + re-encode each message, like a real endpoint
            recv_fn = recv_fn_factory()
            try:
                while not stop:
                    send_fn(b, recv_fn(b))
            except (ConnectionError, OSError):
                pass

        t = threading.Thread(target=echo, daemon=True)
        t.start()
        recv_fn = recv_fn_factory()
        try:
            send_fn(a, tree)   # warmup round-trip
            recv_fn(a)
            t0 = time.perf_counter()
            for _ in range(rounds):
                send_fn(a, tree)
                recv_fn(a)
            dt = time.perf_counter() - t0
        finally:
            stop.append(True)
            a.close()
            b.close()
        # Payload bytes crossing the wire per round trip: out + back.
        return 2 * tree_bytes * rounds / dt / 1e6

    legacy = measure(legacy_send, lambda: legacy_recv)
    zero_copy = measure(zc_send, make_zc_recv)
    result = {
        "metric": f"ps_wire round-trip ({tree_bytes / 2**20:.0f} MiB dense "
                  f"pytree, {n_layers} layers)",
        "unit": "MB/s",
        "rows": {"legacy": round(legacy, 1), "zero_copy": round(zero_copy, 1)},
        "speedup": round(zero_copy / legacy, 3),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("ps_wire")
        if recorded:
            rec = recorded["speedup"]
            threshold = recorded.get("threshold_pct", 15.0)
            result["vs_recorded_speedup"] = round(result["speedup"] / rec, 4)
            if result["speedup"] < rec * (1.0 - threshold / 100.0):
                print(f"WARNING: ps_wire speedup {result['speedup']:.2f}x is "
                      f"more than {threshold}% below the recorded {rec:.2f}x "
                      f"— the zero-copy wire path regressed (see "
                      f"PERF_BASELINE.json ps_wire)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def telemetry_overhead(steps: int = 150):
    """Telemetry cost micro-bench (CPU micro-model, host-dispatch-bound — the
    same shape class as the unroll sweep's CPU leg, so step time is dominated
    by exactly the host path the spans instrument):

    - steps/s through ``runner.run`` with telemetry DISABLED (production
      default) and ENABLED (span ring + registry recording),
    - the disabled span construct's direct cost in ns (1e5 no-op
      ``with telemetry.span(...)`` blocks), and the implied
      ``disabled_overhead_pct`` — span cost x spans-per-step as a fraction of
      the measured step time. This is the gated number: the recorded
      ``telemetry_overhead`` row in PERF_BASELINE.json carries
      ``max_disabled_overhead_pct`` (2.0), and exceeding it means the
      disabled fast path stopped being a single attribute check.

    The steps/s pair is cross-checked against the recorded rows only on a
    matching platform (absolute CPU rates are machine-specific); the span-ns
    gate is machine-relative by construction so it gates everywhere."""
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=batch)
    state = runner.init(params)

    def measure(n):
        nonlocal state
        loss = None
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = runner.run(state, batch)
        _ = jax.device_get(loss)   # completion fence
        return n / (time.perf_counter() - t0)

    was_enabled = telemetry.enabled()
    telemetry.disable()
    measure(10)                    # compile + warmup
    rate_disabled = measure(steps)
    telemetry.enable()
    measure(3)
    rate_enabled = measure(steps)
    telemetry.clear()

    # Direct disabled-construct cost, independent of host-load noise in the
    # steps/s pair: N no-op spans, ns each.
    telemetry.disable()
    n_spans = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(n_spans):
        with telemetry.span("bench"):
            pass
    span_ns = (time.perf_counter_ns() - t0) / n_spans
    if was_enabled:
        telemetry.enable()

    # Spans per step on the instrumented per-step train path: train.data_wait,
    # train.dispatch, runner.shard_batch, runner.run.dispatch, plus headroom
    # for the meter's boundary readback and async-PS client spans.
    spans_per_step = 8
    step_ns = 1e9 / rate_disabled
    disabled_overhead_pct = 100.0 * span_ns * spans_per_step / step_ns

    result = {
        "metric": f"telemetry_overhead ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size})",
        "unit": "steps/s",
        "rows": {"disabled": round(rate_disabled, 2),
                 "enabled": round(rate_enabled, 2)},
        "enabled_vs_disabled": round(rate_enabled / rate_disabled, 4),
        "disabled_span_ns": round(span_ns, 1),
        "spans_per_step": spans_per_step,
        "disabled_overhead_pct": round(disabled_overhead_pct, 4),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("telemetry_overhead")
        if recorded:
            max_pct = recorded.get("max_disabled_overhead_pct", 2.0)
            if disabled_overhead_pct > max_pct:
                print(f"WARNING: disabled-mode telemetry overhead "
                      f"{disabled_overhead_pct:.3f}% of step time exceeds the "
                      f"{max_pct}% gate — the disabled span fast path "
                      f"regressed (see PERF_BASELINE.json "
                      f"telemetry_overhead)", file=sys.stderr)
            floor = recorded.get("enabled_vs_disabled_floor")
            if (floor and recorded.get("platform") == platform
                    and result["enabled_vs_disabled"] < floor):
                print(f"WARNING: enabled-telemetry steps/s is "
                      f"{result['enabled_vs_disabled']:.2f}x the disabled "
                      f"rate, below the recorded {floor:.2f}x floor — "
                      f"enabled-mode recording got costlier (see "
                      f"PERF_BASELINE.json telemetry_overhead)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def health_overhead(steps: int = 60, rounds: int = 3):
    """Training-health monitor cost micro-bench (the CPU transformer
    micro-model at a training-shaped batch):

    - ``bundle_ms`` — the DIRECT cost of the fused numerics bundle
      (``telemetry.health.device_bundle`` jitted over the model's own
      param-shaped trees, min of ``rounds`` timed loops), and the implied
      ``overhead_pct`` = bundle time as a fraction of the measured
      monitors-DISABLED step time. This is the gated number: the recorded
      ``health_overhead`` row in PERF_BASELINE.json carries
      ``max_overhead_pct`` (2.0) — the bundle growing past ~2% of a
      host-bound step means it stopped being a few fused reductions (the
      same machine-relative construction as the telemetry row's span-ns
      gate, so it gates everywhere).
    - the steps/s pair through ``runner.run`` with monitors disabled vs
      enabled (best of ``rounds`` interleaved rounds, the enabled side
      paying a real monitor boundary each round) — cross-checked against
      the recorded ratio floor only on a matching platform: absolute
      steps/s pairs are load-noisy on shared boxes, so the ratio floor is
      a wide backstop against gross fusion/donation regressions, not the
      primary gate.
    """
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import health as _health

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    # A training-shaped batch (not the dispatch-stress micro shape): the
    # bundle's cost is O(params) and independent of the batch, so the gate
    # ratio must be taken against a step doing a real batch's work.
    batch_size, seq_len = 32 * n_dev, 32
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)

    def build(health: bool):
        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(
            loss_fn, params, optax.adam(1e-3), example_batch=batch,
            health=health)
        return runner, runner.init(params)

    monitor = _health.HealthMonitor(_health.HealthConfig(action="warn"))
    runners = {False: build(False), True: build(True)}

    def measure(health: bool, n: int) -> float:
        runner, state = runners[health]
        loss = None
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = runner.run(state, batch)
        if health:
            # The boundary work a real train() period pays: one bundle
            # readback + the host-side monitor pass (inside the timed
            # window, so the pair covers the WHOLE enabled cost; the
            # device_get doubles as the completion fence).
            monitor.observe(n, [float(jax.device_get(loss))],
                            jax.device_get(runner.last_health))
        else:
            _ = jax.device_get(loss)   # completion fence
        dt = time.perf_counter() - t0
        runners[health] = (runner, state)
        return n / dt

    measure(False, 5)   # compile + warmup both programs
    measure(True, 5)
    best = {False: 0.0, True: 0.0}
    for _ in range(rounds):            # interleaved: load noise hits both
        best[False] = max(best[False], measure(False, steps))
        best[True] = max(best[True], measure(True, steps))
    telemetry.clear()

    # Direct bundle cost on the model's own tree shapes (min-of-rounds —
    # load spikes stretch a round, never shrink one).
    tree = runners[True][1].params
    bundle_fn = jax.jit(_health.device_bundle)
    out = bundle_fn(tree, tree, tree, jnp.float32(1.0))
    jax.block_until_ready(out)
    bundle_ms = math.inf
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(100):
            out = bundle_fn(tree, tree, tree, jnp.float32(1.0))
        jax.block_until_ready(out)
        bundle_ms = min(bundle_ms, (time.perf_counter() - t0) * 10.0)
    step_ms = 1e3 / best[False]
    overhead_pct = 100.0 * bundle_ms / step_ms

    result = {
        "metric": f"health_overhead ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size})",
        "unit": "steps/s",
        "rows": {"disabled": round(best[False], 2),
                 "enabled": round(best[True], 2)},
        "enabled_vs_disabled": round(best[True] / best[False], 4),
        "bundle_ms": round(bundle_ms, 4),
        "overhead_pct": round(overhead_pct, 3),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("health_overhead")
        if recorded:
            max_pct = recorded.get("max_overhead_pct", 2.0)
            if overhead_pct > max_pct:
                print(f"WARNING: the fused health bundle costs "
                      f"{overhead_pct:.2f}% of a host-bound step, above the "
                      f"{max_pct}% gate — it grew beyond a few fused "
                      f"reductions (see PERF_BASELINE.json health_overhead)",
                      file=sys.stderr)
            floor = recorded.get("enabled_vs_disabled_floor")
            if (floor and recorded.get("platform") == platform
                    and result["enabled_vs_disabled"] < floor):
                print(f"WARNING: health-enabled steps/s is "
                      f"{result['enabled_vs_disabled']:.2f}x the disabled "
                      f"rate, below the recorded {floor:.2f}x floor — "
                      f"enabled-mode monitoring got costlier (see "
                      f"PERF_BASELINE.json health_overhead)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def attr_overhead(steps: int = 120, log_every: int = 40, rounds: int = 3):
    """Performance-attribution plane cost micro-bench (the CPU transformer
    micro-model, host-dispatch-bound — the shape class where per-dispatch
    overhead is most visible):

    - steps/s through ``runner.run`` with the attribution plane DISABLED
      (production default: telemetry fully off) and ENABLED
      (``profiling.enable()`` — span ring + per-dispatch signature/cost
      accounting + a real ``observe_period`` boundary per round), best of
      ``rounds`` interleaved rounds;
    - the DIRECT enabled-side costs, machine-relative so they gate
      everywhere: ``note_ns`` (one per-dispatch signature count) and
      ``observe_ms`` (one log-boundary attribution pass over a
      ``log_every``-step period's spans), combined as ``overhead_pct`` =
      (note_ns + observe_ms/log_every) over the measured disabled step
      time. This is the gated number: the ``attr_overhead`` row in
      PERF_BASELINE.json carries ``max_overhead_pct`` (2.0) — attribution
      growing past ~2%% of a host-bound step means the boundary join
      stopped being a columnar-ring scan.

    With ``AUTODIST_PROFILE_DIR`` set, the enabled run's profile JSON is
    written there (the ci.sh adprof self-diff smoke reads it)."""
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import profiling

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=batch)
    state = runner.init(params)

    def measure(n, boundary=False):
        nonlocal state
        loss = None
        t0 = time.perf_counter()
        for _ in range(n):
            state, loss = runner.run(state, batch)
        _ = jax.device_get(loss)   # completion fence
        if boundary:
            # The boundary work a real train() period pays, inside the
            # timed window so the pair covers the WHOLE enabled cost.
            profiling.observe_period()
        return n / (time.perf_counter() - t0)

    was_enabled = telemetry.enabled()
    telemetry.disable()
    profiling.disable()
    measure(10)                    # compile + warmup
    profiling.enable()             # also enables spans
    profiling.reset()
    measure(3, boundary=True)
    profiling.disable()
    telemetry.disable()
    best = {"disabled": 0.0, "enabled": 0.0}
    for _ in range(rounds):        # interleaved: load noise hits both sides
        best["disabled"] = max(best["disabled"], measure(steps))
        profiling.enable()
        best["enabled"] = max(best["enabled"], measure(steps, boundary=True))
        profiling.disable()
        telemetry.disable()

    # Direct boundary cost: a log_every-step period's spans, one
    # observe_period pass (min of rounds — load stretches, never shrinks).
    profiling.enable()
    observe_ms = math.inf
    for _ in range(rounds):
        measure(log_every)
        t0 = time.perf_counter()
        rec = profiling.observe_period()
        observe_ms = min(observe_ms, (time.perf_counter() - t0) * 1e3)
    shares = rec["shares"] if rec else None
    mfu = rec.get("mfu") if rec else None
    profile_path = profiling.maybe_write_profile()

    # Direct per-dispatch cost of the signature count.
    n_notes = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(n_notes):
        profiling.note_dispatch("bench-sig", "step", 1)
    note_ns = (time.perf_counter_ns() - t0) / n_notes
    profiling.reset()
    profiling.disable()
    telemetry.clear()
    if was_enabled:
        telemetry.enable()
    else:
        telemetry.disable()

    step_ns = 1e9 / best["disabled"]
    overhead_pct = 100.0 * (note_ns + observe_ms * 1e6 / log_every) / step_ns

    result = {
        "metric": f"attr_overhead ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size}, "
                  f"log_every {log_every})",
        "unit": "steps/s",
        "rows": {"disabled": round(best["disabled"], 2),
                 "enabled": round(best["enabled"], 2)},
        "enabled_vs_disabled": round(best["enabled"] / best["disabled"], 4),
        "note_ns": round(note_ns, 1),
        "observe_ms": round(observe_ms, 4),
        "overhead_pct": round(overhead_pct, 4),
        "attr": shares,
    }
    if profile_path:
        result["profile"] = profile_path
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("attr_overhead")
        if recorded:
            max_pct = recorded.get("max_overhead_pct", 2.0)
            if overhead_pct > max_pct:
                print(f"WARNING: the attribution plane costs "
                      f"{overhead_pct:.3f}% of a host-bound step, above the "
                      f"{max_pct}% gate — per-dispatch counting or the "
                      f"boundary span join got costlier (see "
                      f"PERF_BASELINE.json attr_overhead)", file=sys.stderr)
            floor = recorded.get("enabled_vs_disabled_floor")
            if (floor and recorded.get("platform") == platform
                    and result["enabled_vs_disabled"] < floor):
                print(f"WARNING: attribution-enabled steps/s is "
                      f"{result['enabled_vs_disabled']:.2f}x the disabled "
                      f"rate, below the recorded {floor:.2f}x floor (see "
                      f"PERF_BASELINE.json attr_overhead)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["disabled"],
                        "unit": "steps/s", "mfu": mfu, "attr": shares,
                        "overhead_pct": result["overhead_pct"]})
    return result


def mem_overhead(steps: int = 120, log_every: int = 40, rounds: int = 3):
    """Memory-plane cost micro-bench (the CPU transformer micro-model,
    host-dispatch-bound — where any per-boundary cost is most visible):

    - steps/s through ``runner.run`` with the plane IDLE (no claims, no
      attribution — the production default) and ARMED (the train loop's
      boundary work: re-tag params + opt_state census claims and one
      ``sample_device_memory`` pass, whose attribution decomposes the live
      bytes over the claims and books ``mem.owned.*`` + ``mem.pressure``),
      best of ``rounds`` interleaved rounds;
    - the DIRECT armed-side costs, machine-relative so they gate
      everywhere: ``tag_ms`` (one params + opt_state re-tag — tree walk +
      weakref registration) and ``sample_ms`` (one full
      ``sample_device_memory`` with the attribution pass), combined as
      ``overhead_pct`` = (tag_ms + sample_ms) / log_every over the
      measured idle step time. The gated number: the ``mem_overhead`` row
      in PERF_BASELINE.json carries ``max_overhead_pct`` (2.0) — the
      census growing past ~2% of a host-bound step means attribution
      stopped being one live-array walk over a handful of claims.
    """
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import memplane

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=batch)
    state = runner.init(params)

    def measure(n, boundary=False):
        nonlocal state
        loss = None
        t0 = time.perf_counter()
        for i in range(n):
            state, loss = runner.run(state, batch)
            if boundary and (i + 1) % log_every == 0:
                # The boundary work an armed train() period pays, at the
                # period rate, inside the timed window: re-point the
                # census claims at this boundary's (donation-fresh) state
                # and run the sampler whose attribution pass walks them.
                memplane.tag("params", state.params)
                memplane.tag("opt_state", state.opt_state)
                telemetry.sample_device_memory(opt_state=state.opt_state)
        _ = jax.device_get(loss)   # completion fence
        return n / (time.perf_counter() - t0)

    try:
        measure(10)                         # compile + warmup
        measure(log_every, boundary=True)   # warm the boundary path too
        best = {"disabled": 0.0, "enabled": 0.0}
        for _ in range(rounds):    # interleaved: load noise hits both sides
            best["disabled"] = max(best["disabled"], measure(steps))
            best["enabled"] = max(best["enabled"],
                                  measure(steps, boundary=True))

        # Direct boundary costs (min of rounds — load stretches, never
        # shrinks).
        tag_ms = sample_ms = math.inf
        for _ in range(rounds):
            t0 = time.perf_counter()
            memplane.tag("params", state.params)
            memplane.tag("opt_state", state.opt_state)
            tag_ms = min(tag_ms, (time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            telemetry.sample_device_memory(opt_state=state.opt_state)
            sample_ms = min(sample_ms, (time.perf_counter() - t0) * 1e3)
        census = memplane.census()
    finally:
        memplane.reset()

    step_ms = 1e3 / best["disabled"]
    overhead_pct = 100.0 * (tag_ms + sample_ms) / log_every / step_ms

    result = {
        "metric": f"mem_overhead ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size}, "
                  f"log_every {log_every})",
        "unit": "steps/s",
        "rows": {"disabled": round(best["disabled"], 2),
                 "enabled": round(best["enabled"], 2)},
        "enabled_vs_disabled": round(best["enabled"] / best["disabled"], 4),
        "tag_ms": round(tag_ms, 4),
        "sample_ms": round(sample_ms, 4),
        "owners": sorted(census),
        "overhead_pct": round(overhead_pct, 4),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("mem_overhead")
        if recorded:
            max_pct = recorded.get("max_overhead_pct", 2.0)
            if overhead_pct > max_pct:
                print(f"WARNING: the memory plane costs "
                      f"{overhead_pct:.3f}% of a host-bound step, above the "
                      f"{max_pct}% gate — census tagging or the attribution "
                      f"pass got costlier (see PERF_BASELINE.json "
                      f"mem_overhead)", file=sys.stderr)
            floor = recorded.get("enabled_vs_disabled_floor")
            if (floor and recorded.get("platform") == platform
                    and result["enabled_vs_disabled"] < floor):
                print(f"WARNING: census-armed steps/s is "
                      f"{result['enabled_vs_disabled']:.2f}x the idle "
                      f"rate, below the recorded {floor:.2f}x floor (see "
                      f"PERF_BASELINE.json mem_overhead)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["disabled"],
                        "unit": "steps/s",
                        "tag_ms": result["tag_ms"],
                        "sample_ms": result["sample_ms"],
                        "overhead_pct": result["overhead_pct"]})
    return result


def metrics_overhead(steps: int = 120, log_every: int = 40, rounds: int = 3):
    """Fleet-metrics-plane cost micro-bench (the CPU transformer micro-model,
    host-dispatch-bound — where any per-boundary cost is most visible):

    - steps/s through ``runner.run`` with the plane DISABLED (production
      default: no history, no alerting) and ENABLED (a MetricsHistory with
      JSONL shards + the SHIPPED alert rule set sampling at every
      ``log_every`` boundary, plus one OpenMetrics render per boundary —
      the worst case of a scraper polling exactly at boundary rate), best
      of ``rounds`` interleaved rounds;
    - the DIRECT enabled-side costs, machine-relative so they gate
      everywhere: ``sample_ms`` (one registry snapshot + ring append +
      shard line + full default-rule alert evaluation) and ``render_ms``
      (one exposition render of the populated registry), combined as
      ``overhead_pct`` = (sample_ms + render_ms) / log_every over the
      measured disabled step time. This is the gated number: the
      ``metrics_overhead`` row in PERF_BASELINE.json carries
      ``max_overhead_pct`` (2.0) — the plane growing past ~2% of a
      host-bound step means sampling stopped being one snapshot walk.
    """
    import shutil
    import sys
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import alerts, history, openmetrics

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=batch)
    state = runner.init(params)

    tmp = tempfile.mkdtemp(prefix="metrics_bench_")
    engine = alerts.AlertEngine(rules=alerts.load_rules(""), action="warn")
    hist = history.MetricsHistory(out_dir=tmp, min_interval_s=0.0,
                                  engine=engine)

    def measure(n, boundary=False):
        nonlocal state
        loss = None
        t0 = time.perf_counter()
        for i in range(n):
            state, loss = runner.run(state, batch)
            if boundary and (i + 1) % log_every == 0:
                # The boundary work a real armed train() period pays, AT
                # the period rate — sample (+ alert tick + shard line) and
                # one scrape-rate render per log_every steps, inside the
                # timed window so the pair covers the WHOLE enabled cost.
                hist.sample(step=i + 1)
                openmetrics.render()
        _ = jax.device_get(loss)   # completion fence
        return n / (time.perf_counter() - t0)

    try:
        measure(10)                    # compile + warmup
        measure(log_every, boundary=True)   # warm the boundary path too
        best = {"disabled": 0.0, "enabled": 0.0}
        for _ in range(rounds):    # interleaved: load noise hits both sides
            best["disabled"] = max(best["disabled"], measure(steps))
            best["enabled"] = max(best["enabled"],
                                  measure(steps, boundary=True))

        # Direct boundary costs (min of rounds — load stretches, never
        # shrinks).
        sample_ms = render_ms = math.inf
        for _ in range(rounds):
            t0 = time.perf_counter()
            hist.sample()
            sample_ms = min(sample_ms, (time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            text = openmetrics.render()
            render_ms = min(render_ms, (time.perf_counter() - t0) * 1e3)
        n_shards = len(hist.shards())
    finally:
        hist.close()
        shutil.rmtree(tmp, ignore_errors=True)   # CI runs this every pass

    step_ms = 1e3 / best["disabled"]
    overhead_pct = 100.0 * (sample_ms + render_ms) / log_every / step_ms

    result = {
        "metric": f"metrics_overhead ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size}, "
                  f"log_every {log_every})",
        "unit": "steps/s",
        "rows": {"disabled": round(best["disabled"], 2),
                 "enabled": round(best["enabled"], 2)},
        "enabled_vs_disabled": round(best["enabled"] / best["disabled"], 4),
        "sample_ms": round(sample_ms, 4),
        "render_ms": round(render_ms, 4),
        "render_bytes": len(text),
        "rules": len(engine.rules),
        "shards": n_shards,
        "overhead_pct": round(overhead_pct, 4),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("metrics_overhead")
        if recorded:
            max_pct = recorded.get("max_overhead_pct", 2.0)
            if overhead_pct > max_pct:
                print(f"WARNING: the fleet metrics plane costs "
                      f"{overhead_pct:.3f}% of a host-bound step, above the "
                      f"{max_pct}% gate — history sampling or the exporter "
                      f"render got costlier (see PERF_BASELINE.json "
                      f"metrics_overhead)", file=sys.stderr)
            floor = recorded.get("enabled_vs_disabled_floor")
            if (floor and recorded.get("platform") == platform
                    and result["enabled_vs_disabled"] < floor):
                print(f"WARNING: metrics-enabled steps/s is "
                      f"{result['enabled_vs_disabled']:.2f}x the disabled "
                      f"rate, below the recorded {floor:.2f}x floor (see "
                      f"PERF_BASELINE.json metrics_overhead)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["disabled"],
                        "unit": "steps/s",
                        "sample_ms": result["sample_ms"],
                        "render_ms": result["render_ms"],
                        "overhead_pct": result["overhead_pct"]})
    return result


def trace_pull_overhead(rounds: int = 5):
    """Cluster-trace pull cost micro-bench: fill the span ring to its full
    capacity (AUTODIST_TELEMETRY_RING, default 65536 spans) and measure

    - ``stall_ms`` — the CHIEF-SIDE blocking work of serving one ``trace``
      opcode: columnar ring snapshot (``telemetry.local_trace_state``) +
      zero-copy wire encode. This is the piece that competes with training
      for the chief's GIL/CPU, so it is the gated number: the recorded
      ``trace_pull`` row in PERF_BASELINE.json carries ``max_stall_ms``
      (50.0) — a full-ring pull must never stall training longer than that.
    - ``pull_ms`` — a worker's full round-trip (request, snapshot, encode,
      loopback socket, alias decode) against a real PSServer over a
      numpy-only stub runner, for the end-to-end picture.

    Pure host/CPU work; the columnar blob layout (name/tid tables + ndarray
    columns instead of 65536 per-span tuples) is exactly what this bench
    exists to defend."""
    import sys

    from autodist_tpu import const, telemetry
    from autodist_tpu.parallel import wire

    cap = int(const.ENV.AUTODIST_TELEMETRY_RING.val)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    telemetry.clear()
    for i in range(cap):
        # Every 8th span carries args: realistic rings are mostly bare spans
        # with occasional annotated ones.
        if i & 7:
            with telemetry.span("bench.fill"):
                pass
        else:
            with telemetry.span("bench.fill", step=i):
                pass

    # Chief-side blocking cost: snapshot + encode (what the serving thread
    # does while training shares the process). MIN across rounds: the
    # intrinsic cost is what the gate defends; host-load spikes on a shared
    # CI box are not trace-plane regressions.
    stall_samples = []
    blob_bytes = 0
    for _ in range(max(rounds, 7)):
        t0 = time.perf_counter()
        state = telemetry.local_trace_state()
        parts = wire.encode_parts(("ok", state))
        stall_samples.append((time.perf_counter() - t0) * 1e3)
        blob_bytes = sum(len(p) for p in parts)
    stall_ms = min(stall_samples)

    # End-to-end loopback pull through a real PSServer.
    class _StubPSRunner:
        def __init__(self):
            from autodist_tpu.parallel.staleness import (ParameterService,
                                                         StalenessController)
            from autodist_tpu.runner import TrainState
            state = TrainState(step=np.zeros((), np.int32),
                               params={"w": np.ones((8,), np.float32)},
                               opt_state=(), ef_state=())
            self.service = ParameterService(state, lambda s, g: s)
            self.controller = StalenessController(1, staleness=1)

        def add_worker(self, worker_id=None, with_generation=False):
            wid, gen = self.controller.register_with_generation(worker_id)
            handle = type("H", (), {"worker_id": wid})()
            return (handle, gen) if with_generation else handle

    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    server = PSServer(_StubPSRunner(), host="127.0.0.1", watchdog=False)
    remote = RemotePSWorker("%s:%d" % server.address, runner=None,
                            worker_id=0, overlap=False)
    try:
        remote.trace()                      # warmup
        pull_samples = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            blob = remote.trace()
            pull_samples.append((time.perf_counter() - t0) * 1e3)
        n_spans = len(blob["name_idx"])
    finally:
        remote.close()
        server.close()
        telemetry.clear()
        if not was_enabled:
            telemetry.disable()
    pull_ms = sorted(pull_samples)[len(pull_samples) // 2]

    result = {
        "metric": f"trace_pull ({n_spans}-span ring, "
                  f"{blob_bytes / 2**20:.2f} MiB blob)",
        "unit": "ms",
        "rows": {"stall_ms": round(stall_ms, 2), "pull_ms": round(pull_ms, 2)},
        "ring": n_spans,
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("trace_pull")
        if recorded:
            max_stall = recorded.get("max_stall_ms", 50.0)
            if stall_ms > max_stall:
                print(f"WARNING: full-ring trace snapshot+encode took "
                      f"{stall_ms:.1f}ms, over the {max_stall}ms stall gate — "
                      f"a trace pull would stall training (see "
                      f"PERF_BASELINE.json trace_pull; did the columnar blob "
                      f"layout regress to per-span encoding?)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def reqtrace_overhead(requests: int = 24, clients: int = 4):
    """Request-trace plane cost bench (the serving analogue of
    --telemetry-overhead):

    - requests/s at a fixed offered load through a real 1-replica
      Router + RouterServer fleet with the request-trace ring DISARMED
      (production default) and ARMED (``AUTODIST_REQTRACE=1``: lifecycle
      marks at every hop plus the wire trace token on each forwarded
      generate),
    - the disarmed ``reqtrace.mark`` direct cost in ns (1e5 calls — the
      one-attribute-read contract) and the armed per-mark cost, and
    - the implied ``armed_overhead_pct``: armed mark cost x the marks the
      fleet actually booked per request (counted from the ring, so new
      instrumentation sites raise the bill automatically) as a fraction of
      the measured mean request latency. This is the gated number — the
      ``reqtrace_overhead`` row in PERF_BASELINE.json carries
      ``max_overhead_pct`` (2.0), and exceeding it means tracing a request
      stopped being a handful of deque appends.

    The rps pair is cross-checked against the recorded
    ``armed_vs_disarmed_floor`` only as a wide backstop — closed-loop
    loopback serving on a shared CPU box is noisy — so the
    machine-relative direct-cost percentage is the hard gate."""
    import sys
    import threading

    import jax
    import jax.numpy as jnp

    from autodist_tpu import serving
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.serving.router import Router, RouterServer
    from autodist_tpu.telemetry import reqtrace

    platform = jax.devices()[0].platform
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=2, n_layers=2, d_ff=256,
        max_len=128, dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)

    def replica_factory():
        scfg = serving.ServeConfig(max_batch=4, temperature=0.0)
        batcher = serving.Batcher(
            serving.LMEngine(model, params, scfg), scfg)
        return serving.InferenceServer(batcher)

    def offered_load(router_server, n, max_new):
        ok, errors = [], []
        lock = san_lock()

        def client_thread(wid):
            c = serving.ServeClient(router_server.address)
            try:
                for i in range(wid, n, clients):
                    try:
                        prompt = np.arange(1, 9, dtype=np.int32) + i % 40
                        tokens, _ = c.generate(prompt, max_new, seed=i)
                        with lock:
                            ok.append(tokens)
                    except serving.ServeError as e:
                        with lock:
                            errors.append(str(e))
            finally:
                c.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client_thread, args=(w,))
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return ok, errors, time.perf_counter() - t0

    was_armed = reqtrace.enabled()
    reqtrace.disable()
    walls = {}
    router = Router(replica_factory, n_replicas=1, start=False)
    server = RouterServer(router)
    try:
        for rep in router.replicas():      # compile off the clock
            warm = serving.ServeClient(rep.address)
            try:
                warm.generate(np.arange(1, 9, dtype=np.int32), 2)
            finally:
                warm.close()
        for mode in ("disarmed", "armed"):
            if mode == "armed":
                reqtrace.enable()
                reqtrace.clear()
            ok, errors, wall = offered_load(server, requests, 8)
            if errors or len(ok) != requests:
                raise RuntimeError(
                    f"reqtrace bench ({mode}): {len(ok)}/{requests} ok, "
                    f"errors: {errors[:3]}")
            walls[mode] = wall
        marks_per_request = len(reqtrace.snapshot_marks()) / requests
    finally:
        server.close()
        reqtrace.clear()
        reqtrace.disable()

    # Direct per-mark costs, independent of loopback-serving noise: N marks
    # each way, ns per call. The disarmed number IS the one-attribute-read
    # contract; the armed number prices the intern lookup + deque appends.
    n_marks = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(n_marks):
        reqtrace.mark("bench", "queued")
    disarmed_mark_ns = (time.perf_counter_ns() - t0) / n_marks
    reqtrace.enable()
    t0 = time.perf_counter_ns()
    for _ in range(n_marks):
        reqtrace.mark("bench", "queued")
    armed_mark_ns = (time.perf_counter_ns() - t0) / n_marks
    reqtrace.clear()
    if not was_armed:
        reqtrace.disable()

    # clients closed-loop threads are busy for the whole wall, so total
    # request-seconds ~= wall x clients and the mean latency follows.
    request_ns = walls["armed"] * clients / requests * 1e9
    armed_overhead_pct = 100.0 * armed_mark_ns * marks_per_request / request_ns

    result = {
        "metric": f"reqtrace_overhead ({platform}, 1-replica fleet, "
                  f"{requests} req x {clients} clients)",
        "unit": "req/s",
        "rows": {"disarmed": round(requests / walls["disarmed"], 2),
                 "armed": round(requests / walls["armed"], 2)},
        "armed_vs_disarmed": round(walls["disarmed"] / walls["armed"], 4),
        "disarmed_mark_ns": round(disarmed_mark_ns, 1),
        "armed_mark_ns": round(armed_mark_ns, 1),
        "marks_per_request": round(marks_per_request, 1),
        "armed_overhead_pct": round(armed_overhead_pct, 4),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("reqtrace_overhead")
        if recorded:
            max_pct = recorded.get("max_overhead_pct", 2.0)
            if armed_overhead_pct > max_pct:
                print(f"WARNING: armed request-trace overhead "
                      f"{armed_overhead_pct:.3f}% of request latency exceeds "
                      f"the {max_pct}% gate — a lifecycle mark stopped being "
                      f"a handful of deque appends (see PERF_BASELINE.json "
                      f"reqtrace_overhead)", file=sys.stderr)
            floor = recorded.get("armed_vs_disarmed_floor")
            if (floor and recorded.get("platform") == platform
                    and result["armed_vs_disarmed"] < floor):
                print(f"WARNING: armed-reqtrace req/s is "
                      f"{result['armed_vs_disarmed']:.2f}x the disarmed "
                      f"rate, below the recorded {floor:.2f}x floor — armed "
                      f"recording got costlier on the serving path (see "
                      f"PERF_BASELINE.json reqtrace_overhead)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def zero_update_bench(steps: int = 60, dp: int = 2):
    """ZeRO weight-update sharding (arXiv 2004.13336) memory/step bench.

    Runs the CPU micro-model (same shape class as the other micro-benches)
    twice on a dp-device mesh — ``zero=0`` (replicated optimizer update,
    today's default) and ``zero=1`` (reduce-scatter -> shard-local update ->
    all-gather) — and reports:

    - ``opt_bytes``: per-device resident optimizer-state bytes
      (``telemetry.opt_state_bytes`` — max over devices of the shard bytes
      each holds), unsharded vs sharded. The GATED number is their ratio:
      the recorded ``zero_update`` row carries ``min_opt_bytes_ratio``
      (1.5 at dp=2; the ideal is ~dp, less the replicated scalar leaves),
      and falling below it means the plan stopped sharding the moments.
    - ``steps_s``: steps/s for both runs (informational — on CPU the
      collectives the constraint points insert are host work, so sharded is
      expected to cost a few percent; on real pods the reduce-scatter is
      cheaper than the all-reduce it replaces).
    - ``live_bytes``: per-device resident bytes over ALL live arrays after
      each run (max over devices of the shard bytes each holds — the same
      accounting as the PR 5 ``device.live_bytes`` gauge family).
      Informational only: on the CPU backend the dispatch device also holds
      tracing/executable residue (a params-sized constant copy survives
      session construction), which blurs whole-process accounting — the
      clean, gated signal is ``opt_bytes``.

    Needs >= dp local devices: when the host exposes fewer (plain
    ``python bench.py --zero`` on a 1-CPU box), dp CPU devices are simulated
    via XLA_FLAGS before the backend initializes — which is why this must
    run before any other jax-touching bench in the same process."""
    import os
    import sys

    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={dp}").strip()
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist, telemetry
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    if n_dev < dp:
        print(json.dumps({"metric": "zero_update", "skipped":
                          f"needs >= {dp} devices, found {n_dev} (jax was "
                          f"already initialized before --zero could simulate "
                          f"them)"}))
        return None
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    # Source params live on the host: a device-0 jnp copy would sit in
    # jax.live_arrays() across both runs and dominate the per-device max.
    params = jax.tree_util.tree_map(np.asarray, params)

    def measure(zero):
        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(
            loss_fn, params, optax.adam(1e-3), example_batch=batch, zero=zero)
        state = runner.init(params)
        opt_bytes = telemetry.opt_state_bytes(state.opt_state)
        loss = None
        for _ in range(5):          # compile + warmup
            state, loss = runner.run(state, batch)
        _ = jax.device_get(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = runner.run(state, batch)
        _ = jax.device_get(loss)
        rate = steps / (time.perf_counter() - t0)
        # Per-device resident bytes (a sharded array's global .nbytes would
        # count every shard on every device and hide the saving). Collect
        # first: the previous run's donated-buffer cycles otherwise linger
        # in jax.live_arrays() and mask the difference.
        import gc
        gc.collect()
        live = telemetry.opt_state_bytes(jax.live_arrays())
        del state
        return opt_bytes, rate, live

    bytes_plain, rate_plain, live_plain = measure(0)
    bytes_zero, rate_zero, live_zero = measure(1)
    ratio = bytes_plain / max(1, bytes_zero)

    result = {
        "metric": f"zero_update ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size}, adam)",
        "unit": "bytes/device",
        "rows": {"opt_bytes_unsharded": bytes_plain,
                 "opt_bytes_sharded": bytes_zero},
        "opt_bytes_ratio": round(ratio, 3),
        "steps_s": {"unsharded": round(rate_plain, 2),
                    "sharded": round(rate_zero, 2)},
        "live_bytes": {"unsharded": live_plain, "sharded": live_zero},
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("zero_update")
        if recorded:
            floor = recorded.get("min_opt_bytes_ratio", 1.5)
            if ratio < floor:
                print(f"WARNING: ZeRO opt-state per-device bytes ratio "
                      f"{ratio:.2f}x is below the {floor:.2f}x gate at "
                      f"dp={n_dev} — weight-update sharding stopped dividing "
                      f"the optimizer state (see PERF_BASELINE.json "
                      f"zero_update)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def serve_bench(requests: int = 32, clients: int = 8, max_batch: int = 4):
    """Serving-plane bench: loopback requests/s and p99 total latency at a
    fixed offered load, STATIC vs CONTINUOUS batching on the tiny LM.

    One shared :class:`~autodist_tpu.serving.runtime.LMEngine` (so both modes
    pay the same compiled programs and the same per-step device cost) is
    driven through a real :class:`~autodist_tpu.serving.InferenceServer` by
    ``clients`` closed-loop client threads — each its own connection, the
    subsystem's intended concurrency model. The workload alternates short and
    long generations (8 vs 48 new tokens), the mix that exposes the convoy
    effect: a static wave drains at the pace of its longest member while
    freed slots sit idle, whereas continuous admission refills them between
    decode steps. The GATE (recorded ``serving`` row in PERF_BASELINE.json)
    is that continuous batching beats static on requests/s at
    equal-or-better p99 — the property the whole batcher design exists for.
    Each mode is measured over 3 interleaved rounds and the best round is
    reported (the same best-of-N discipline the unroll/telemetry benches use
    on this load-noisy box class — decode-step counts, not host scheduling
    luck, are what the gate compares). Greedy decode, CPU-safe, no
    accelerator required."""
    import sys
    import threading

    import jax.numpy as jnp

    from autodist_tpu import serving
    from autodist_tpu.models import transformer_lm

    import jax
    platform = jax.devices()[0].platform

    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=2, n_layers=2, d_ff=256,
        max_len=128, dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)
    scfg = serving.ServeConfig(max_batch=max_batch, temperature=0.0)
    engine = serving.LMEngine(model, params, scfg)

    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, size=int(rng.randint(4, 48)))
               .astype(np.int32) for _ in range(requests)]
    # Long generations dominate wall time: a static wave of 4 costs its
    # longest member's 48 steps while freed slots idle; continuous refills
    # them, so it runs ~len(mix)/fill fewer decode dispatches.
    max_new = [8 if i % 2 == 0 else 48 for i in range(requests)]

    def measure(mode):
        import dataclasses
        batcher = serving.Batcher(
            engine, dataclasses.replace(scfg, mode=mode))
        server = serving.InferenceServer(batcher)
        timings, errors = [], []
        lock = san_lock()

        def client_thread(wid):
            c = serving.ServeClient(server.address)
            try:
                for i in range(wid, requests, clients):
                    try:
                        _, timing = c.generate(prompts[i], max_new[i], seed=i)
                        with lock:
                            timings.append(timing)
                    except serving.ServeError as e:
                        with lock:
                            errors.append(str(e))
            finally:
                c.close()

        # Warm every jitted program off the clock (one prefill per touched
        # bucket + decode + insert) through the full transport path.
        warm = serving.ServeClient(server.address)
        try:
            for b in sorted({serving.bucket_for(len(p), engine.buckets)
                             for p in prompts}):
                warm.generate(np.arange(1, 1 + b, dtype=np.int32), 2)
        finally:
            warm.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client_thread, args=(w,))
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        server.close()
        if errors or len(timings) != requests:
            raise RuntimeError(
                f"serve bench ({mode}): {len(timings)}/{requests} ok, "
                f"errors: {errors[:3]}")
        totals_ms = sorted(t["total_s"] * 1e3 for t in timings)
        p99 = totals_ms[min(len(totals_ms) - 1,
                            int(round(0.99 * (len(totals_ms) - 1))))]
        return round(requests / wall, 2), round(p99, 1)

    # 3 interleaved rounds per mode; the best round each (max rps, min p99)
    # is the gated pair — load spikes on a shared box hit one round, not
    # both modes' best.
    static_runs, cont_runs = [], []
    for _ in range(3):
        static_runs.append(measure("static"))
        cont_runs.append(measure("continuous"))
    static_rps = max(r for r, _ in static_runs)
    static_p99 = min(p for _, p in static_runs)
    cont_rps = max(r for r, _ in cont_runs)
    cont_p99 = min(p for _, p in cont_runs)

    result = {
        "metric": f"serving ({platform}, d{cfg.d_model}x{cfg.n_layers}, "
                  f"{max_batch} slots, {clients} clients, {requests} reqs, "
                  f"8/48-token mix, best of 3)",
        "unit": "requests/s",
        "rows": {"static_rps": static_rps, "continuous_rps": cont_rps,
                 "static_p99_ms": static_p99, "continuous_p99_ms": cont_p99},
        "rps_ratio": round(cont_rps / max(1e-9, static_rps), 3),
        "p99_ratio": round(cont_p99 / max(1e-9, static_p99), 3),
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("serving")
        if recorded:
            min_rps = recorded.get("min_rps_ratio", 1.0)
            max_p99 = recorded.get("max_p99_ratio", 1.0)
            if result["rps_ratio"] < min_rps:
                print(f"WARNING: continuous batching throughput is "
                      f"{result['rps_ratio']:.2f}x static — below the "
                      f"{min_rps:.2f}x gate; decode-step admission stopped "
                      f"paying for itself (see PERF_BASELINE.json serving)",
                      file=sys.stderr)
            if result["p99_ratio"] > max_p99:
                print(f"WARNING: continuous batching p99 is "
                      f"{result['p99_ratio']:.2f}x static — above the "
                      f"{max_p99:.2f}x gate; early-exit slot reuse stopped "
                      f"improving tail latency (see PERF_BASELINE.json "
                      f"serving)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def serve_fleet_bench(requests: int = 24, fleet_requests: int = 16,
                      clients: int = 4):
    """Fleet-serving bench (PR 17): three legs, gated against the
    ``serve_fleet`` row in PERF_BASELINE.json.

    1. PAGED vs DENSE concurrency at the SAME KV HBM budget. The dense
       engine owns ``4 x max_len`` slot-rows; the paged engine owns the same
       token count as pages (plus the scratch page) and admits on RESERVABLE
       PAGES, so short requests pack ``>= min_concurrency_ratio`` times more
       concurrent work into the identical memory. Both engines serve the
       identical request set through a real Batcher and the gate REQUIRES
       bit-identical token streams — the capacity win is worthless if the
       math changed (this is a RuntimeError, not a warning).
    2. Router 2-replica vs 1-replica offered-load rps through a real
       RouterServer + unchanged ServeClients. On a shared-core CPU box the
       replicas contend for the same host, so the recorded floor is a wide
       "adding a replica must not collapse throughput" guard, not a 2x pin
       (on real fleets each replica owns its chips).
    3. Kill-a-replica: ``clients`` closed-loop clients against a 2-replica
       fleet; one replica is killed with requests IN FLIGHT. The contract
       (RuntimeError on violation, same discipline as the selfheal bench):
       every request completes, ZERO client-visible failures, and the
       recovery plane books >= 1 respawn — the router replayed the severed
       requests (same rid, replica-side dedup) onto the survivor and healed
       the fleet."""
    import sys
    import threading

    import jax.numpy as jnp

    from autodist_tpu import serving
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.parallel import recovery as _recovery
    from autodist_tpu.serving.router import Router, RouterServer

    import jax
    platform = jax.devices()[0].platform

    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=2, n_layers=2, d_ff=256,
        max_len=128, dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)

    # ---- leg 1: paged vs dense concurrency at equal KV HBM -------------
    # Dense: 4 slots x 128 tokens = 512 KV rows. Paged: 32 usable 16-token
    # pages = the same 512 rows (+1 scratch page), but a 2-page request
    # only OCCUPIES 2 pages, so 16 of them run concurrently.
    dense_cfg = serving.ServeConfig(max_batch=4, temperature=0.0)
    paged_cfg = serving.ServeConfig(max_batch=16, temperature=0.0,
                                    page_len=16, kv_pages=33)
    rng = np.random.RandomState(0)
    workload = [(rng.randint(1, cfg.vocab_size,
                             size=int(rng.randint(6, 15))).astype(np.int32),
                 12, i) for i in range(requests)]

    def run_engine(engine, scfg):
        batcher = serving.Batcher(engine, scfg, start=False)
        reqs = [batcher.submit(p, n, seed=s) for p, n, s in workload]
        peak = 0
        for _ in range(4000):
            if all(r.done.is_set() for r in reqs):
                break
            batcher.run_once()
            peak = max(peak, len(batcher.in_flight_snapshot()))
        bad = [r.error for r in reqs if r.error or not r.done.is_set()]
        if bad:
            raise RuntimeError(f"serve-fleet bench: engine leg failed: "
                               f"{bad[:3]}")
        return [tuple(r.tokens) for r in reqs], peak

    dense_tokens, dense_peak = run_engine(
        serving.LMEngine(model, params, dense_cfg), dense_cfg)
    paged_tokens, paged_peak = run_engine(
        serving.PagedLMEngine(model, params, paged_cfg), paged_cfg)
    if paged_tokens != dense_tokens:
        raise RuntimeError(
            "serve-fleet bench: paged tokens diverged from dense — the "
            "paged KV cache broke bit-identity (see serving/paged.py)")
    concurrency_ratio = round(paged_peak / max(1, dense_peak), 3)

    # ---- legs 2+3: router fleet rps and kill-a-replica -----------------
    def replica_factory():
        scfg = serving.ServeConfig(max_batch=4, temperature=0.0)
        batcher = serving.Batcher(
            serving.LMEngine(model, params, scfg), scfg)
        return serving.InferenceServer(batcher)

    def offered_load(router_server, n, max_new):
        ok, errors = [], []
        lock = san_lock()

        def client_thread(wid):
            c = serving.ServeClient(router_server.address)
            try:
                for i in range(wid, n, clients):
                    try:
                        prompt = np.arange(1, 9, dtype=np.int32) + i % 40
                        tokens, _ = c.generate(prompt, max_new, seed=i)
                        with lock:
                            ok.append(tokens)
                    except serving.ServeError as e:
                        with lock:
                            errors.append(str(e))
            finally:
                c.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client_thread, args=(w,))
                   for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return ok, errors, time.perf_counter() - t0

    fleet_rps = {}
    for n_replicas in (1, 2):
        router = Router(replica_factory, n_replicas=n_replicas, start=False)
        server = RouterServer(router)
        try:
            # Warm EVERY replica's programs off the clock, addressed
            # directly — the router's least-loaded tie-break would send
            # every idle sequential warm to replica 0 and leave the
            # others to compile on the clock.
            for rep in router.replicas():
                warm = serving.ServeClient(rep.address)
                try:
                    warm.generate(np.arange(1, 9, dtype=np.int32), 2)
                finally:
                    warm.close()
            ok, errors, wall = offered_load(server, fleet_requests, 8)
            if errors or len(ok) != fleet_requests:
                raise RuntimeError(
                    f"serve-fleet bench ({n_replicas} replica(s)): "
                    f"{len(ok)}/{fleet_requests} ok, errors: {errors[:3]}")
            fleet_rps[n_replicas] = round(fleet_requests / wall, 2)
        finally:
            server.close()
    fleet_ratio = round(fleet_rps[2] / max(1e-9, fleet_rps[1]), 3)

    # Kill leg: requests in flight, one replica dies, nobody notices.
    _recovery.reset()
    old_backoff = Router.RESPAWN_BACKOFF_S
    Router.RESPAWN_BACKOFF_S = 0.05
    try:
        router = Router(replica_factory, n_replicas=2, start=False)
        server = RouterServer(router)
        try:
            for rep in router.replicas():
                warm = serving.ServeClient(rep.address)
                try:
                    warm.generate(np.arange(1, 9, dtype=np.int32), 2)
                finally:
                    warm.close()
            victim = router.replicas()[0]

            def killer():
                deadline = time.monotonic() + 10.0
                while victim.load() == 0 and time.monotonic() < deadline:
                    time.sleep(0.001)
                victim.server.kill()

            kt = threading.Thread(target=killer, name="bench-fleet-killer")
            kt.start()
            try:
                ok, errors, _ = offered_load(server, fleet_requests, 24)
            finally:
                # join unconditionally: a failed load leg used to leak the
                # non-daemon killer past the bench (thread-fence finding)
                kt.join(timeout=15.0)
            counts = _recovery.recovery_snapshot()["counts"]
            if errors or len(ok) != fleet_requests:
                raise RuntimeError(
                    f"serve-fleet bench (kill leg): {len(ok)}/"
                    f"{fleet_requests} completed, errors: {errors[:3]} — "
                    f"a replica death leaked to clients")
            if counts.get("respawns", 0) < 1:
                raise RuntimeError(
                    "serve-fleet bench (kill leg): no respawn booked — the "
                    "kill never landed mid-flight; the leg proved nothing")
        finally:
            server.close()
    finally:
        Router.RESPAWN_BACKOFF_S = old_backoff

    result = {
        "metric": f"serve_fleet ({platform}, d{cfg.d_model}x{cfg.n_layers}, "
                  f"dense 4x{cfg.max_len} vs paged 32x16 pages, "
                  f"{clients} clients)",
        "rows": {"dense_peak": dense_peak, "paged_peak": paged_peak,
                 "fleet1_rps": fleet_rps[1], "fleet2_rps": fleet_rps[2]},
        "concurrency_ratio": concurrency_ratio,
        "fleet_rps_ratio": fleet_ratio,
        "kill_leg": {"completed": len(ok), "respawns": counts["respawns"],
                     "evicted": counts["evicted"]},
    }
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("serve_fleet")
        if recorded:
            floor = recorded.get("min_concurrency_ratio", 1.5)
            if concurrency_ratio < floor:
                print(f"WARNING: paged concurrency is "
                      f"{concurrency_ratio:.2f}x dense at equal KV HBM — "
                      f"below the {floor:.2f}x gate; page packing stopped "
                      f"paying for itself (see PERF_BASELINE.json "
                      f"serve_fleet)", file=sys.stderr)
            rps_floor = recorded.get("min_fleet_rps_ratio", 0.5)
            if fleet_ratio < rps_floor:
                print(f"WARNING: 2-replica rps is {fleet_ratio:.2f}x "
                      f"1-replica — below the {rps_floor:.2f}x guard; "
                      f"routing overhead is eating the fleet (see "
                      f"PERF_BASELINE.json serve_fleet)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "concurrency_ratio": concurrency_ratio,
                        "fleet_rps_ratio": fleet_ratio,
                        "fleet2_rps": fleet_rps[2],
                        "kill_respawns": counts["respawns"]})
    return result


def unroll_sweep(factors):
    """Measure the fused multi-step path (``runner.run_many``) at each unroll
    factor and print ONE JSON line with the steps/s curve.

    On accelerators this uses the flagship model (accum off — the sweep
    isolates dispatch amortization); on CPU a tiny model whose step is
    host-dispatch-bound, so the curve measures exactly the overhead ``unroll``
    amortizes, not chip throughput. The curve is diffed against the recorded
    ``unroll_curve`` in PERF_BASELINE.json when the platform matches: the
    gate metric is the max-factor SPEEDUP over unroll=1 (machine-relative, so
    it transfers across hosts of the same platform class better than raw
    rates)."""
    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.ops import mosaic_compiles
    from autodist_tpu.strategy import AllReduce

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    on_accel = platform != "cpu"
    if on_accel:
        cfg = transformer_lm.TransformerLMConfig(
            vocab_size=32_000, d_model=512, n_heads=8, n_layers=6, d_ff=2048,
            max_len=512, dtype=jnp.bfloat16, tied_output=False,
            fused_head=mosaic_compiles())
        batch_size, seq_len, total_steps = 384 * n_dev, 256, 160
    else:
        cfg = transformer_lm.TransformerLMConfig(
            vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_len=64, dtype=jnp.float32, tied_output=False)
        batch_size, seq_len, total_steps = 8 * n_dev, 16, 192

    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=batch)
    state = runner.init(params)

    rows = {}
    for k in factors:
        block = runner.shard_block([batch] * k)
        state, losses = runner.run_many(state, block)   # compile + warmup
        _ = jax.device_get(losses)
        n_blocks = max(3, total_steps // k)
        t0 = time.perf_counter()
        for _ in range(n_blocks):
            state, losses = runner.run_many(state, block)
        _ = jax.device_get(losses)   # completion fence (see main())
        dt = time.perf_counter() - t0
        rows[str(k)] = round(n_blocks * k / dt, 2)

    result = {
        "metric": f"unroll_sweep ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size})",
        "unit": "steps/s",
        "rows": rows,
        "tokens_per_step": batch_size * seq_len,
    }
    if "1" in rows:
        # The gate metric is the MAX factor's speedup (the factor the recorded
        # baseline was measured at), so a regression confined to the deepest
        # unroll cannot hide behind a healthy shallower factor; best_factor
        # stays informational (the argmax-rate factor).
        max_f = max(int(f) for f in rows)
        result["best_factor"] = max((int(f) for f in rows),
                                    key=lambda f: rows[str(f)])
        result["speedup_vs_unroll1"] = round(rows[str(max_f)] / rows["1"], 4)
        try:
            import sys
            with open(_baseline_path()) as f:
                recorded = json.load(f).get("unroll_curve")
            if recorded and recorded.get("platform") == platform:
                rec_speedup = recorded["speedup_vs_unroll1"]
                threshold = recorded.get("threshold_pct", 5.0)
                result["vs_recorded_speedup"] = round(
                    result["speedup_vs_unroll1"] / rec_speedup, 4)
                if result["speedup_vs_unroll1"] < \
                        rec_speedup * (1.0 - threshold / 100.0):
                    print(f"WARNING: unroll speedup "
                          f"{result['speedup_vs_unroll1']:.2f}x is more than "
                          f"{threshold}% below the recorded "
                          f"{rec_speedup:.2f}x — the fused multi-step path "
                          f"regressed (see PERF_BASELINE.json unroll_curve)",
                          file=sys.stderr)
        except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
            pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    return result


def autotune_bench(rounds: int = 3, steps: int = 48):
    """Plan-autotuner gate: tuned plan vs default plan steps/s on the CPU
    micro-model (the host-dispatch-bound shape class where the knob space —
    unroll amortization above all — has real headroom).

    Runs one full predict-prune-probe search (``strategy.autotune``) with a
    throwaway plan cache, then measures the DEFAULT plan (the session's
    PSLoadBalancing builder, ``unroll=1``) and the TUNED winner back-to-back
    (best of ``rounds`` interleaved rounds, ~``steps`` optimizer steps each,
    through the tuner's shared probe loop so both sides pay identical
    harness cost). Gated numbers in the PERF_BASELINE.json ``autotune``
    row:

    - ``tuned_vs_default`` >= ``min_ratio`` (1.0): the searched plan must
      never lose to the default it replaces;
    - ``probed`` <= ``top_k``: stage-1 pruning must hold — at most top-k of
      the enumerated candidates get measured probe steps (the search-cost
      contract; ``search_s`` reports the wall cost)."""
    import sys
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import const
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import PSLoadBalancing
    from autodist_tpu.strategy.autotune import autotune
    from autodist_tpu.strategy.tuner import measure_candidate

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                           seq_len=seq_len)

    top_k = int(const.ENV.AUTODIST_TUNE_TOPK.val)
    with tempfile.TemporaryDirectory() as tmp:
        plan = autotune(loss_fn, params, optax.adam(1e-3), batch,
                        plan_cache=f"{tmp}/plan_cache.json",
                        warmup_steps=2, measure_steps=6)

    def measure(builder, unroll, zero, accum):
        n = max(4, steps // unroll)
        r = measure_candidate(builder, loss_fn, params, optax.adam(1e-3),
                              batch, warmup_steps=2, measure_steps=n,
                              unroll=unroll, zero=zero,
                              accumulation_steps=accum)
        return r.steps_per_sec or 0.0

    best = {"default": 0.0, "tuned": 0.0}
    for _ in range(rounds):   # interleaved: load noise hits both sides
        best["default"] = max(best["default"],
                              measure(PSLoadBalancing(), 1, 0, 1))
        best["tuned"] = max(best["tuned"],
                            measure(plan.make_builder(), plan.unroll,
                                    plan.zero, plan.accumulation_steps))

    ratio = best["tuned"] / best["default"] if best["default"] else 0.0
    result = {
        "metric": f"autotune ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size})",
        "unit": "steps/s",
        "rows": {"default": round(best["default"], 2),
                 "tuned": round(best["tuned"], 2)},
        "tuned_vs_default": round(ratio, 4),
        "plan": plan.name,
        "predicted_step_ms": round((plan.predicted or {}).get("step_s", 0.0)
                                   * 1e3, 4),
        "search_s": round(plan.search_s, 2),
        "enumerated": plan.enumerated,
        "probed": plan.probed,
        "top_k": top_k,
    }
    if plan.probed > top_k:
        print(f"WARNING: autotune measured-probed {plan.probed} candidates, "
              f"above top_k={top_k} — stage-1 pruning stopped bounding the "
              f"search cost (see strategy/autotune.py)", file=sys.stderr)
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("autotune")
        if recorded and recorded.get("platform") == platform:
            floor = recorded.get("min_ratio", 1.0)
            if ratio < floor:
                print(f"WARNING: tuned plan is {ratio:.2f}x the default "
                      f"plan's steps/s, below the {floor:.2f}x floor — the "
                      f"autotuner picked a losing plan (see "
                      f"PERF_BASELINE.json autotune)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["tuned"],
                        "unit": "steps/s", "plan": plan.name,
                        "tuned_vs_default": result["tuned_vs_default"],
                        "search_s": result["search_s"],
                        "probed": plan.probed})
    return result


def data_plane_bench(steps: int = 96, log_every: int = 32, rounds: int = 3,
                     sleep_ms: float = 4.0, depth: int = 4):
    """Input-data plane gate: an injected slow host loader (a fixed
    per-batch sleep — the MLPerf pod bottleneck in miniature) fed to
    ``train()`` synchronously (``prefetch_depth=0``) vs through the async
    prefetch producer (``prefetch_depth=depth``), best of ``rounds``
    interleaved rounds. Gated numbers in the PERF_BASELINE.json
    ``data_plane`` row:

    - ``prefetch_vs_sync`` >= ``min_ratio`` (1.2): the producer must
      actually hide the injected stall behind the running step;
    - the prefetched leg's ``train.attr.data_wait`` share must sit BELOW
      ``max_data_wait_share`` — the shipped ``data_wait_drift`` alert's
      band, so the rule that pages on a sync slow loader stays quiet on
      the prefetched one;
    - ``data.producer_wait`` must still carry >= half the injected loader
      seconds: hiding the stall must not hide the SLOW LOADER (the
      counter is how attribution keeps naming it);
    - the two legs' final params must be BIT-IDENTICAL (prefetching
      reorders nothing — same batches, same math, same order)."""
    import sys

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from autodist_tpu import AutoDist, telemetry, training
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.strategy import AllReduce
    from autodist_tpu.telemetry import alerts, profiling

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_len=64, dtype=jnp.float32, tied_output=False)
    batch_size, seq_len = 8 * n_dev, 16
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    uniques = [transformer_lm.synthetic_batch(cfg, batch_size=batch_size,
                                              seq_len=seq_len, seed=s)
               for s in range(4)]
    sleep_s = sleep_ms / 1e3

    def slow_batches(i):
        time.sleep(sleep_s)      # the injected loader stall
        return uniques[i % len(uniques)]

    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss_fn, params, optax.adam(1e-3),
                                           example_batch=uniques[0])

    was_enabled = telemetry.enabled()
    profiling.enable()    # attribution on: the gate reads data_wait shares

    def leg(depth_):
        """One timed train() run from the same start params; returns
        (steps/s, period-weighted data_wait share, producer_wait delta,
        final params)."""
        profiling.reset()
        wait0 = telemetry.counter("data.producer_wait").value
        t0 = time.perf_counter()
        final = training.train(runner, params, slow_batches, steps,
                               log_every=log_every, prefetch_depth=depth_)
        dt = time.perf_counter() - t0
        periods = profiling.attribution_periods()
        total_s = sum(p["period_s"] for p in periods)
        share = (sum(p["shares"]["data_wait"] * p["period_s"]
                     for p in periods) / total_s) if total_s else None
        wait_s = telemetry.counter("data.producer_wait").value - wait0
        return steps / dt, share, wait_s, jax.device_get(
            runner.logical_params(final))

    leg(0)   # compile + warmup (both loops share the compiled step)
    best = {"sync": 0.0, "prefetched": 0.0}
    sync_share = pf_share = None
    producer_wait_s = 0.0
    params_sync = params_pf = None
    for _ in range(rounds):   # interleaved: load noise hits both sides
        rate, share, _, params_sync = leg(0)
        if rate > best["sync"]:
            best["sync"], sync_share = rate, share
        rate, share, wait_s, params_pf = leg(depth)
        if rate > best["prefetched"]:
            best["prefetched"], pf_share = rate, share
            producer_wait_s = wait_s
    profiling.reset()
    profiling.disable()
    telemetry.clear()
    if was_enabled:
        telemetry.enable()
    else:
        telemetry.disable()

    flat_a = jax.tree_util.tree_leaves(params_sync)
    flat_b = jax.tree_util.tree_leaves(params_pf)
    bit_identical = len(flat_a) == len(flat_b) and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(flat_a, flat_b))
    band = next(r["band"] for r in alerts.DEFAULT_RULES
                if r["name"] == "data_wait_drift")
    ratio = best["prefetched"] / best["sync"] if best["sync"] else 0.0
    injected_s = steps * sleep_s

    result = {
        "metric": f"data_plane ({platform} x{n_dev}, d{cfg.d_model}"
                  f"x{cfg.n_layers}, seq{seq_len}, bs{batch_size}, "
                  f"loader sleep {sleep_ms:g}ms, depth {depth})",
        "unit": "steps/s",
        "rows": {"sync": round(best["sync"], 2),
                 "prefetched": round(best["prefetched"], 2)},
        "prefetch_vs_sync": round(ratio, 4),
        "data_wait_share": {"sync": round(sync_share, 4)
                            if sync_share is not None else None,
                            "prefetched": round(pf_share, 4)
                            if pf_share is not None else None},
        "drift_band": band,
        "producer_wait_s": round(producer_wait_s, 3),
        "injected_loader_s": round(injected_s, 3),
        "bit_identical": bit_identical,
    }
    if not bit_identical:
        print("WARNING: prefetched final params are NOT bit-identical to "
              "the synchronous path's — the producer reordered or altered "
              "batches (see data/prefetch.py ordering contract)",
              file=sys.stderr)
    if pf_share is not None and pf_share >= band:
        print(f"WARNING: prefetched data_wait share {pf_share:.3f} is not "
              f"below the data_wait_drift band ({band}) — the shipped "
              f"alert would still page under prefetch", file=sys.stderr)
    if producer_wait_s < 0.5 * injected_s:
        print(f"WARNING: data.producer_wait booked {producer_wait_s:.2f}s "
              f"of the {injected_s:.2f}s injected loader stall — the slow "
              f"loader is no longer visible in producer telemetry",
              file=sys.stderr)
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("data_plane")
        if recorded and recorded.get("platform") == platform:
            floor = recorded.get("min_ratio", 1.2)
            if ratio < floor:
                print(f"WARNING: prefetched path is {ratio:.2f}x the sync "
                      f"steps/s under the injected slow loader, below the "
                      f"{floor:.2f}x floor — the producer stopped hiding "
                      f"the stall (see PERF_BASELINE.json data_plane)",
                      file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["prefetched"],
                        "unit": "steps/s",
                        "prefetch_vs_sync": result["prefetch_vs_sync"],
                        "data_wait_share": result["data_wait_share"],
                        "producer_wait_s": result["producer_wait_s"]})
    return result


def selfheal_bench(steps_per_worker: int = 60, crash_at: int = 25,
                   dim: int = 256):
    """Self-healing runtime gate: kill one async-PS worker mid-run with the
    REAL fault harness (``testing/faults.py`` — abrupt socket teardown, the
    server sees exactly what a killed process produces), let the recovery
    plane evict it and the supervising harness respawn a replacement that
    re-registers and catches up on the chief's LIVE params over the
    ``read_min`` path, and measure what the incident cost. Gated numbers in
    the PERF_BASELINE.json ``selfheal`` row:

    - the faulted run COMPLETES (every planned step applied) with FINITE
      final params — the acceptance property itself;
    - ``post_vs_free``: steps/s from the crash moment to the end of the
      faulted run must be >= ``min_ratio`` (0.6) of the fault-free run's
      steps/s — eviction + rejoin + catch-up must cost a blip, not the run;
    - the recovery plane actually acted: >= 1 eviction and >= 1 rejoin
      booked (driving real failures is the point — a silent pass with no
      membership action means the fault never fired)."""
    import sys
    import threading

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.parallel import recovery
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    from autodist_tpu.strategy import PS
    from autodist_tpu.testing import faults

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(0)
    w_true = rng.randn(dim, 1).astype(np.float32)

    def batch_for(seed):
        r = np.random.RandomState(seed)
        x = r.randn(64, dim).astype(np.float32)
        return {"x": x, "y": x @ w_true + 0.01 * r.randn(64, 1)
                .astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)

    def params_init():
        return {"w": np.zeros((dim, 1), np.float32)}

    n_workers = 2

    def run_leg(crash):
        """One full run: ``n_workers`` remote workers over a loopback
        PSServer, ``steps_per_worker`` steps each; with ``crash``, worker 1
        dies at its step ``crash_at`` and the harness respawns a
        replacement (the coordinator's AUTODIST_WORKER_FAILURE=respawn
        policy in miniature — in-process so the bench is subprocess-free).
        Returns (total steps/s, post-crash steps/s, final params)."""
        # Fresh recovery log per leg: the clean legs' teardown books
        # disconnect retires too, and the acted-check below must measure
        # THIS leg's fault, not accumulated teardown noise.
        recovery.reset()
        ad = AutoDist(strategy_builder=PS(staleness=4))
        runner = ad.create_distributed_session(
            loss_fn, params_init(), optax.sgd(0.05),
            example_batch=batch_for(0), num_workers=n_workers)
        runner.init(params_init())
        server = PSServer(runner, host="127.0.0.1", watchdog=False)
        addr = "%s:%d" % server.address
        if crash:
            faults.install(f"worker_crash@step={crash_at},worker=1")
        crash_t = {}

        def drive(worker_id):
            worker = RemotePSWorker(addr, runner, worker_id=worker_id)
            i = 0
            try:
                while i < steps_per_worker:
                    try:
                        worker.step(batch_for(worker_id * 10_000 + i),
                                    timeout=120)
                        i += 1
                    except faults.WorkerCrashed:
                        crash_t["t"] = time.perf_counter()
                        crash_t["applies"] = runner.service.updates_applied
                        deadline = time.time() + 30
                        while worker_id not in runner.controller._retired \
                                and time.time() < deadline:
                            time.sleep(0.005)
                        # Bounded backoff, then the replacement registers
                        # and catches up over read_min (the
                        # RemotePSWorker.rejoin path runs inside
                        # register+first pull).
                        time.sleep(recovery.backoff_s(0, 0.05, cap_s=0.2))
                        worker = RemotePSWorker(addr, runner,
                                                worker_id=worker_id)
            finally:
                worker.close()

        try:
            t0 = time.perf_counter()
            threads = [threading.Thread(target=drive, args=(wid,),
                                        name=f"bench-selfheal-{wid}")
                       for wid in range(n_workers)]
            try:
                for t in threads:
                    t.start()
            finally:
                # join in a finally: a start() failure or interrupt must
                # not leak the already-running non-daemon drive threads
                for t in threads:
                    if t.is_alive():
                        t.join()
            dt = time.perf_counter() - t0
            total = runner.service.updates_applied
            post_rate = None
            if crash and "t" in crash_t:
                post_rate = (total - crash_t["applies"]) \
                    / max(1e-9, time.perf_counter() - crash_t["t"])
            final = jax.device_get(runner.service.state.params)
            # Leg-scoped recovery counts. NOTE: "evicted" includes the
            # drive threads' clean-close disconnect retires, not just the
            # crash — the REJOIN count is the fault-specific signal (only a
            # retired slot's re-registration books one, and nothing in a
            # clean leg retires before re-registering).
            counts = recovery.recovery_snapshot()["counts"]
            return total / dt, post_rate, total, final, counts
        finally:
            faults.clear()
            server.close()
            runner.close()

    run_leg(False)   # warmup: absorbs first-process costs (native build,
    #                  transport setup) so the two timed legs pay equally
    free_rate, _, free_total, _, _ = run_leg(False)
    fault_rate, post_rate, fault_total, final, rec = run_leg(True)

    finite = all(np.isfinite(np.asarray(l)).all()
                 for l in jax.tree_util.tree_leaves(final))
    completed = fault_total == n_workers * steps_per_worker
    ratio = (post_rate or 0.0) / free_rate if free_rate else 0.0

    result = {
        "metric": f"selfheal ({platform}, {n_workers} workers x "
                  f"{steps_per_worker} steps, dim {dim}, worker 1 killed "
                  f"at step {crash_at})",
        "unit": "steps/s",
        "rows": {"fault_free": round(free_rate, 2),
                 "faulted_total": round(fault_rate, 2),
                 "post_eviction": round(post_rate or 0.0, 2)},
        "post_vs_free": round(ratio, 4),
        "completed": completed,
        "finite_params": finite,
        "evicted": rec["evicted"],
        "rejoined": rec["rejoined"],
    }
    if not completed:
        print(f"WARNING: faulted run applied {fault_total} of "
              f"{n_workers * steps_per_worker} planned steps — the "
              f"replacement did not finish the crashed worker's share",
              file=sys.stderr)
    if not finite:
        print("WARNING: faulted run's final params are not finite — the "
              "catch-up pull adopted corrupt state", file=sys.stderr)
    if rec["rejoined"] < 1:
        # The rejoin is the discriminating check: clean teardown books
        # disconnect evictions too, but only the crashed slot's replacement
        # re-registers a RETIRED slot.
        print("WARNING: recovery plane booked no rejoin — the injected "
              "crash never exercised the self-heal path", file=sys.stderr)
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("selfheal")
        if recorded and recorded.get("platform") == platform:
            floor = recorded.get("min_ratio", 0.6)
            if ratio < floor:
                print(f"WARNING: post-eviction throughput is {ratio:.2f}x "
                      f"the fault-free rate, below the {floor:.2f}x floor "
                      f"— eviction/rejoin/catch-up got expensive (see "
                      f"PERF_BASELINE.json selfheal)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["post_eviction"],
                        "unit": "steps/s",
                        "post_vs_free": result["post_vs_free"],
                        "evicted": rec["evicted"],
                        "rejoined": rec["rejoined"]})
    return result


def wire_compress_bench(steps: int = 30, rounds: int = 3, dim: int = 512,
                        out_dim: int = 512, bytes_per_s: float = 25e6):
    """Priced wire-compression gate: loopback async-PS training under an
    injected slow wire (the ``wire_slow`` fault point throttles every
    ``_send_payload`` to ``bytes_per_s``), exact pushes vs int8+EF
    compressed pushes, best of ``rounds`` interleaved rounds. The gated
    numbers in the PERF_BASELINE.json ``wire_compress`` row:

    - ``compressed_vs_exact``: compressed steps/s must be >=
      ``min_ratio`` (1.2) x exact — under a wire-bound run the 4x push-byte
      cut must buy real throughput, not just smaller counters;
    - ``bytes_saved`` must be > 0 and agree with the dense-minus-wire
      accounting (the same ``ps.wire.bytes_saved`` counter adtop/adfleet
      render and the cost model's ``quantize_bytes_per_s`` fit reads);
    - both legs' final params stay finite (EF keeps the compressed run a
      faithful optimizer, not a faster diverging one).

    The same trade the autotuner prices: on a fast wire the quantize
    seconds are NOT paid back (tests pin that it declines); this bench
    injects the slow-wire regime where compression must win."""
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    from autodist_tpu.parallel.synchronization import WirePushCompressor
    from autodist_tpu.strategy import PS
    from autodist_tpu.testing import faults

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(0)
    w_true = rng.randn(dim, out_dim).astype(np.float32)
    batch = {"x": rng.randn(32, dim).astype(np.float32)}
    batch["y"] = batch["x"] @ w_true

    def loss_fn(p, b):
        return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)

    dense_bytes = dim * out_dim * 4

    def run_leg(wire_dtype):
        """One timed leg: a fresh loopback session, throttled wire, and an
        explicitly injected compressor (exact = inactive)."""
        ad = AutoDist(strategy_builder=PS(sync=False))
        runner = ad.create_distributed_session(
            loss_fn, {"w": np.zeros((dim, out_dim), np.float32)},
            optax.sgd(0.01), example_batch=batch, num_workers=1)
        runner.init({"w": np.zeros((dim, out_dim), np.float32)})
        server = PSServer(runner, host="127.0.0.1", watchdog=False)
        comp = WirePushCompressor(wire_dtype, min_bytes=1024)
        worker = RemotePSWorker("%s:%d" % server.address, runner,
                                worker_id=0, overlap=False, compressor=comp)
        try:
            worker.warmup(batch)
            faults.install(f"wire_slow@bytes_per_s={bytes_per_s}")
            t0 = time.perf_counter()
            for _ in range(steps):
                worker.step(batch, timeout=120)
            dt = time.perf_counter() - t0
            final = jax.device_get(runner.service.state.params)
            finite = all(np.isfinite(np.asarray(l)).all()
                         for l in jax.tree_util.tree_leaves(final))
            return steps / dt, comp, finite
        finally:
            faults.clear()
            worker.close()
            server.close()
            runner.close()

    run_leg("")   # warmup leg: first-process transport/compile costs
    exact_rate, int8_rate = 0.0, 0.0
    comp = None
    finite_all = True
    for _ in range(rounds):   # interleaved best-of: load noise hits both
        r, _, f1 = run_leg("")
        exact_rate = max(exact_rate, r)
        r, c, f2 = run_leg("int8")
        if r > int8_rate:
            int8_rate, comp = r, c
        finite_all = finite_all and f1 and f2

    ratio = int8_rate / exact_rate if exact_rate else 0.0
    result = {
        "metric": f"wire_compress ({platform}, loopback async-PS, "
                  f"{dim}x{out_dim} f32 grads ({dense_bytes // 1024} KiB "
                  f"dense), wire throttled to "
                  f"{bytes_per_s / 1e6:.0f} MB/s, {steps} steps, best of "
                  f"{rounds})",
        "unit": "steps/s",
        "rows": {"exact": round(exact_rate, 2),
                 "int8_ef": round(int8_rate, 2)},
        "compressed_vs_exact": round(ratio, 4),
        "bytes_saved": comp.bytes_saved,
        "bytes_saved_per_step": comp.bytes_saved // steps,
        "finite_params": finite_all,
    }
    if comp.bytes_saved <= 0 \
            or comp.bytes_saved != comp.bytes_in - comp.bytes_out:
        print("WARNING: bytes_saved accounting is inconsistent "
              f"(in {comp.bytes_in}, out {comp.bytes_out}, saved "
              f"{comp.bytes_saved}) — the compressor's counters no longer "
              "mean dense-minus-wire", file=sys.stderr)
    if not finite_all:
        print("WARNING: a leg's final params are not finite — compression "
              "corrupted the optimizer trajectory", file=sys.stderr)
    try:
        with open(_baseline_path()) as f:
            recorded = json.load(f).get("wire_compress")
        if recorded and recorded.get("platform") == platform:
            floor = recorded.get("min_ratio", 1.2)
            if ratio < floor:
                print(f"WARNING: compressed push is {ratio:.2f}x the exact "
                      f"steps/s under the injected slow wire, below the "
                      f"{floor:.2f}x floor — compression stopped paying for "
                      f"its quantize cost (see PERF_BASELINE.json "
                      f"wire_compress)", file=sys.stderr)
    except (OSError, KeyError, ValueError, TypeError, ZeroDivisionError):
        pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))
    _append_trajectory({"metric": result["metric"],
                        "steps_per_s": result["rows"]["int8_ef"],
                        "unit": "steps/s",
                        "compressed_vs_exact": result["compressed_vs_exact"],
                        "bytes_saved": result["bytes_saved"]})
    return result


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--unroll", type=str, default="",
        help="comma-separated unroll factors (e.g. 1,2,4,8): measure the "
             "fused multi-step path (runner.run_many) at each factor and "
             "print an unroll-curve JSON line instead of the flagship "
             "measurement; on CPU a tiny host-bound model isolates the "
             "dispatch overhead the fusion amortizes")
    parser.add_argument(
        "--wire", action="store_true",
        help="measure the PS transport's zero-copy wire path (encode_parts/"
             "sendmsg/recycled-buffer alias decode) against the legacy "
             "copying codec on a >=32 MiB dense pytree round-trip, and diff "
             "the speedup against the recorded ps_wire row in "
             "PERF_BASELINE.json; CPU-only host work, runs anywhere")
    parser.add_argument(
        "--telemetry-overhead", action="store_true",
        help="measure the host-telemetry cost on the CPU micro-model: "
             "steps/s with telemetry disabled vs enabled plus the disabled "
             "no-op span cost in ns, gated against the telemetry_overhead "
             "row in PERF_BASELINE.json (disabled mode must stay within "
             "max_disabled_overhead_pct of step time)")
    parser.add_argument(
        "--health-overhead", action="store_true",
        help="measure the training-health monitor cost on the CPU "
             "micro-model: steps/s with the fused on-device numerics bundle "
             "disabled vs enabled (best of interleaved rounds), gated "
             "against max_overhead_pct in the PERF_BASELINE.json "
             "health_overhead row (enabled monitors must stay within 2%% "
             "of a host-bound step)")
    parser.add_argument(
        "--attr-overhead", action="store_true",
        help="measure the performance-attribution plane's cost on the CPU "
             "micro-model: steps/s with profiling disabled vs enabled plus "
             "the direct per-dispatch count and per-boundary attribution "
             "costs, gated against max_overhead_pct in the "
             "PERF_BASELINE.json attr_overhead row; writes the enabled "
             "run's profile JSON into AUTODIST_PROFILE_DIR when set (the "
             "adprof self-diff smoke reads it)")
    parser.add_argument(
        "--metrics-overhead", action="store_true",
        help="measure the fleet metrics plane's cost on the CPU micro-model: "
             "steps/s with the plane disabled vs enabled (history sampling + "
             "shipped alert rules + one OpenMetrics render per boundary) "
             "plus the direct per-boundary sample/render costs, gated "
             "against max_overhead_pct in the PERF_BASELINE.json "
             "metrics_overhead row")
    parser.add_argument(
        "--mem-overhead", action="store_true",
        help="measure the memory plane's cost on the CPU micro-model: "
             "steps/s with the census idle vs armed (params + opt_state "
             "re-tag and one attributed sample_device_memory per boundary) "
             "plus the direct per-boundary tag/sample costs, gated against "
             "max_overhead_pct in the PERF_BASELINE.json mem_overhead row")
    parser.add_argument(
        "--trace-pull-overhead", action="store_true",
        help="measure the cluster trace plane's pull cost: fill the span "
             "ring to capacity, report the chief-side snapshot+encode stall "
             "and the loopback round-trip of one `trace` opcode pull, gated "
             "against max_stall_ms in the PERF_BASELINE.json trace_pull row")
    parser.add_argument(
        "--reqtrace-overhead", action="store_true",
        help="measure the request-trace plane's cost on a real 1-replica "
             "router fleet: req/s with the lifecycle ring disarmed vs armed "
             "(AUTODIST_REQTRACE=1) plus the direct per-mark costs, with "
             "the armed mark cost x marks-per-request share of request "
             "latency gated against max_overhead_pct in the "
             "PERF_BASELINE.json reqtrace_overhead row")
    parser.add_argument(
        "--zero", action="store_true",
        help="measure ZeRO weight-update sharding (AUTODIST_ZERO / zero=1) "
             "on the CPU micro-model at simulated dp>=2: per-device "
             "optimizer-state bytes and steps/s, unsharded vs sharded, "
             "gated against min_opt_bytes_ratio in the PERF_BASELINE.json "
             "zero_update row (must run first in a fresh process so the "
             "simulated devices can be created)")
    parser.add_argument(
        "--serve", action="store_true",
        help="measure the serving plane: loopback requests/s and p99 total "
             "latency at a fixed offered load (mixed short/long generations "
             "on the tiny LM through a real InferenceServer), static vs "
             "continuous batching over one shared engine, gated against the "
             "serving row in PERF_BASELINE.json (continuous must beat static "
             "on requests/s at equal-or-better p99)")
    parser.add_argument(
        "--serve-fleet", action="store_true",
        help="measure fleet serving: paged vs dense concurrent requests at "
             "the same KV HBM budget with bit-identical outputs (gated "
             "against min_concurrency_ratio in the PERF_BASELINE.json "
             "serve_fleet row), router 2-replica vs 1-replica rps, and the "
             "kill-a-replica leg (one replica killed with requests in "
             "flight must cost ZERO client-visible failures and book >= 1 "
             "respawn)")
    parser.add_argument(
        "--data-plane", action="store_true",
        help="measure the input-data plane: train() under an injected slow "
             "host loader (fixed per-batch sleep), synchronous feed vs the "
             "async prefetch producer, gated against the data_plane row in "
             "PERF_BASELINE.json (prefetched >= min_ratio x sync steps/s, "
             "data_wait share below the data_wait_drift band, "
             "data.producer_wait still naming the loader, bit-identical "
             "params)")
    parser.add_argument(
        "--selfheal", action="store_true",
        help="measure the self-healing runtime: kill one async-PS worker "
             "mid-run with the fault harness (testing/faults.py), let the "
             "recovery plane evict it and a respawned replacement rejoin + "
             "catch up over read_min, gated against the selfheal row in "
             "PERF_BASELINE.json (run completes with finite params; "
             "post-eviction steps/s >= min_ratio x fault-free)")
    parser.add_argument(
        "--wire-compress", action="store_true",
        help="measure the priced wire-compression path: loopback async-PS "
             "training under an injected slow wire (wire_slow fault point), "
             "exact pushes vs int8+error-feedback compressed pushes, gated "
             "against the wire_compress row in PERF_BASELINE.json "
             "(compressed >= min_ratio x exact steps/s, bytes_saved "
             "accounting consistent, finite params both legs)")
    parser.add_argument(
        "--autotune", action="store_true",
        help="run the plan autotuner's full predict-prune-probe search on "
             "the CPU micro-model and gate the winner: tuned plan steps/s "
             "must be >= min_ratio x the default plan's (PERF_BASELINE.json "
             "autotune row) and stage-1 pruning must measure at most top-k "
             "of the enumerated candidates; reports the search cost")
    parser.add_argument(
        "--profile", type=int, default=0, metavar="N",
        help="dump a jax.profiler trace (Perfetto/TensorBoard format) of an "
             "N-step window after warmup; the trace directory is reported in "
             "the JSON line as profile_trace")
    args = parser.parse_args(argv)
    if args.wire:
        wire_bench()
        return
    if args.telemetry_overhead:
        telemetry_overhead()
        return
    if args.health_overhead:
        health_overhead()
        return
    if args.attr_overhead:
        attr_overhead()
        return
    if args.metrics_overhead:
        metrics_overhead()
        return
    if args.mem_overhead:
        mem_overhead()
        return
    if args.trace_pull_overhead:
        trace_pull_overhead()
        return
    if args.reqtrace_overhead:
        reqtrace_overhead()
        return
    if args.zero:
        zero_update_bench()
        return
    if args.serve:
        serve_bench()
        return
    if args.serve_fleet:
        serve_fleet_bench()
        return
    if args.data_plane:
        data_plane_bench()
        return
    if args.selfheal:
        selfheal_bench()
        return
    if args.wire_compress:
        wire_compress_bench()
        return
    if args.autotune:
        autotune_bench()
        return
    if args.unroll:
        try:
            factors = [int(f) for f in args.unroll.split(",") if f.strip()]
        except ValueError:
            factors = []
        if not factors or any(f < 1 for f in factors):
            parser.error(f"--unroll needs comma-separated positive integers, "
                         f"got {args.unroll!r}")
        unroll_sweep(factors)
        return

    import jax
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.models import transformer_lm
    from autodist_tpu.ops import mosaic_compiles
    from autodist_tpu.strategy import AllReduce

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())

    # lm1b-class flagship config; bf16 activations on accelerators.
    on_accel = platform != "cpu"
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=32_000, d_model=512, n_heads=8, n_layers=6, d_ff=2048,
        max_len=512, dtype=jnp.bfloat16 if on_accel else jnp.float32,
        tied_output=False,
        # Pallas fused head+loss (logits never materialized): measured faster
        # than the XLA head at equal batch (410k vs 398k tokens/s at 256) AND
        # it unlocks batch 384, which OOMs with materialized logits. Gated on
        # the platforms whose Mosaic backend compiles the kernels — elsewhere
        # (GPU) pallas would run in interpret mode and crater the bench.
        fused_head=mosaic_compiles())
    # Swept on a v5e chip: fused head 384/device = ~426k tokens/s vs 410k at
    # 256 and 421k at 512; XLA head topped out at ~404k (bs 256; 384 OOMs);
    # seq512 loses (346k at 128). Gradient accumulation on top (same 384-seq
    # micro-batch, Adam applied once per ACCUM micro-batches) amortizes the
    # optimizer + dispatch: 433.6k@2, 436.3k@3, 441.2k@8, plateau ~442k@16 —
    # accum 8 (global batch 3072 seqs = 786k tokens, a standard large-batch
    # LM config) ships as the flagship.
    seq_len = 256 if on_accel else 64
    accum = 8 if on_accel else 1
    batch_size = (384 if on_accel else 8) * n_dev * accum

    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=batch_size, seq_len=seq_len)

    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch,
                       accumulation_steps=accum)
    # Device-resident batch: measure the chip, not the host link.
    batch = step.runner.shard_batch(batch)

    # Warmup (compile + first dispatch), then timed steps. The final host read is
    # the sync barrier: the last loss depends on the whole state chain, and a
    # device->host transfer is a reliable completion fence even on experimental
    # platforms where block_until_ready has proven optimistic.
    for _ in range(2):
        loss = step(batch)
    _ = float(loss)
    trace_dir = None
    if args.profile > 0:
        # Profiled window AFTER warmup (the trace sees steady-state steps,
        # not compilation) and BEFORE the timed loop (tracing overhead must
        # not contaminate the reported rate).
        from autodist_tpu.utils import tracing
        with tracing.trace("bench_flagship") as trace_dir:
            for _ in range(args.profile):
                loss = step(batch)
            _ = float(loss)  # completion fence inside the traced window
    n_steps = 20 if on_accel else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = step(batch)
    _ = float(loss)
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * seq_len
    tokens_per_sec = tokens_per_step * n_steps / dt
    per_device = tokens_per_sec / n_dev

    # MFU from the analytic per-token count (the fused pallas head is invisible
    # to XLA's flop analysis, so the compiled-module count would under-report).
    from autodist_tpu.utils import flops as flops_util
    flops_per_token = flops_util.transformer_flops_per_token(
        cfg.d_model, cfg.n_layers, cfg.d_ff, cfg.vocab_size, seq_len)
    mfu = flops_util.mfu(flops_per_token * tokens_per_sec / n_dev)

    result = {
        "metric": f"transformer_lm_train_tokens_per_sec ({platform} x{n_dev}, "
                  f"d{cfg.d_model}x{cfg.n_layers}, seq{seq_len}, "
                  f"bs{batch_size}={batch_size // accum}x{accum}accum)",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": round(per_device / BASELINE_TOKENS_PER_SEC_PER_DEVICE, 3),
        "flops_per_token": round(flops_per_token),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    if trace_dir is not None:
        result["profile_trace"] = trace_dir

    # Attribution postscript — AFTER the timed loop, so the trajectory row
    # can say where the step's wall time goes without taxing the reported
    # rate: a short profiled window (3 steps + one observe_period). The
    # analytic per-token count stands in for XLA's where the fused pallas
    # head hides flops from cost analysis. Best-effort: a diagnostics
    # postscript must never fail the flagship measurement.
    attr = None
    try:
        from autodist_tpu import telemetry
        from autodist_tpu.telemetry import profiling
        was_on = telemetry.enabled()
        profiling.enable()
        profiling.reset()
        profiling.set_analytic_flops(flops_per_token * tokens_per_step)
        profiling.observe_period()        # open a clean window
        for _ in range(3):
            loss = step(batch)
        _ = float(loss)
        rec = profiling.observe_period()
        attr = rec["shares"] if rec else None
        profiling.reset()
        profiling.disable()
        if not was_on:
            telemetry.disable()
    except Exception:  # noqa: BLE001
        pass
    _append_trajectory({"metric": result["metric"], "value": result["value"],
                        "unit": "tokens/s", "mfu": result["mfu"],
                        "attr": attr})
    # Regression gate vs the recorded best (PERF_BASELINE.json): annotate the
    # JSON line and warn on stderr past the threshold. Round-over-round drift
    # was previously invisible (428.6k -> 425.8k went unremarked); this line
    # makes a real 2-3% regression impossible to miss. CPU runs measure a
    # different machine entirely — the recorded bests are chip rates.
    if on_accel:
        import sys
        base_path = _baseline_path()
        try:
            with open(base_path) as f:
                base = json.load(f)
            best = base["rows"]["flagship"]["rate"]
            threshold = base.get("threshold_pct", 2.0)
            # The snapshot records PER-CHIP rates; compare per-device so a
            # multi-chip aggregate can't mask a per-chip regression.
            result["vs_best"] = round(per_device / best, 4)
            if per_device < best * (1.0 - threshold / 100.0):
                print(f"WARNING: flagship {per_device:,.0f} tokens/s/chip is "
                      f"{100 * (1 - per_device / best):.1f}% below the "
                      f"recorded best {best:,.0f} (threshold {threshold}%) — "
                      f"see PERF_BASELINE.json", file=sys.stderr)
        except (OSError, KeyError, ValueError, TypeError):
            pass  # a missing/mangled snapshot must not break the bench
    print(json.dumps(result))


if __name__ == "__main__":
    main()
