"""Fully-automatic cross-process async PS (driver in test_multiprocess.py).

Unlike ``async_ps_script.py`` (which wires the transport by hand to port the c9
timing assertion), this script uses ONLY the public surface: a 2-node resource
spec plus ``PS(staleness=...)``. ``create_distributed_session`` detects the
non-synchronous regime, skips the jax.distributed collective program, launches
the worker, ships the PS transport address, serves the chief's parameter
service after init, and routes the worker's ``step`` through the transport —
the reference's end-to-end async protocol (``ps_synchronizer.py:387-458`` over
its grpc plane) with zero manual plumbing.
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const  # noqa: E402
from autodist_tpu.strategy import PS  # noqa: E402

SPEC = ("nodes: [{address: localhost, tpus: 2, chief: true}, "
        "{address: 127.0.0.1, tpus: 2}]")
STEPS = 6
STALENESS = 2
LR = 0.05


def make_batch():
    rng = np.random.RandomState(0)
    x = rng.randn(16).astype(np.float32)
    return {"x": x, "y": (3.0 * x + 2.0).astype(np.float32)}


def loss_fn(p, b):
    return jnp.mean((b["y"] - (b["x"] * p["w"] + p["b"])) ** 2)


def main(out_path: str):
    ad = AutoDist(SPEC, PS(sync=True, staleness=STALENESS))
    params = {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}
    batch = make_batch()
    step = ad.function(loss_fn, params, optax.sgd(LR), example_batch=batch)

    losses = []
    for _ in range(STEPS):
        losses.append(float(step(batch)))
        if not const.is_worker():
            # Host-side gap between chief steps: remote applies land here, which
            # must NOT trip the foreign-state check on the next step (the chief
            # hands back its last returned snapshot, not a checkpoint).
            time.sleep(0.05)

    if const.is_worker():
        with open(out_path + ".worker", "w") as f:
            json.dump({"worker_steps": STEPS, "losses": losses}, f)
        return

    # Chief: wait for the worker process, then record the shared service state.
    if not ad._coordinator.join(timeout=120.0):
        raise RuntimeError("worker process did not finish")
    runner = step.runner
    deadline = time.time() + 30
    while runner.service.version < 2 * STEPS and time.time() < deadline:
        time.sleep(0.05)
    worker_result = json.loads(open(out_path + ".worker").read())
    with open(out_path, "w") as f:
        json.dump({
            "final_version": runner.service.version,
            "chief_steps": STEPS,
            "worker_steps": worker_result["worker_steps"],
            "chief_losses": losses,
            "num_worker_slots": runner.num_workers,
            "w": float(runner.service.state.params["w"]),
        }, f)


if __name__ == "__main__":
    main(sys.argv[1])
