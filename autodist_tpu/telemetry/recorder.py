"""Flight recorder: anomaly-triggered capture of the whole telemetry plane.

Pod-scale practice treats stragglers, input stalls and loss blowups as
ROUTINE events that must be diagnosable after the fact, without a human
having had a profiler attached. The measurement plane (span rings, metrics
registry, cluster trace wire) already records everything needed — this module
snapshots it to disk at the moment an anomaly fires:

- :class:`FlightRecorder` writes SELF-CONTAINED snapshot dirs into a bounded
  latest-K ring (oldest evicted): a merged, Perfetto-loadable cluster trace
  (local ring + every worker ring deposited on the server, anomaly events
  overlaid as instant markers), the full metrics-registry snapshot, the event
  ring as JSONL (``tools/tracedump.py --events`` re-merges it), and an
  env/config manifest — everything the existing tracedump tooling reads.
- Triggers: the PS watchdog's ``ps.anomaly.{stall,straggler}`` events, the
  training-health monitors' ``health.anomaly`` events
  (:mod:`autodist_tpu.telemetry.health`), the manual ``record`` wire opcode
  (``RemotePSWorker.record()``), or a direct :meth:`FlightRecorder.record`
  call. Automatic triggers are debounced (``AUTODIST_RECORDER_MIN_S``) so an
  anomaly storm costs one snapshot per window, not one per step.

Arming: :func:`set_recorder` installs a process recorder explicitly;
``AUTODIST_RECORDER=1`` arms a default one lazily at the first trigger.
Un-armed, :func:`maybe_record` is a no-op costing one global read + one env
check — monitoring must never tax the healthy path.
"""

import json
import os
import shutil
import socket
import sys
import threading
import time
from typing import Any, Dict, Iterable, Optional

from autodist_tpu import const
from autodist_tpu.telemetry import cluster as _cluster
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["FlightRecorder", "set_recorder", "get_recorder", "get_or_create",
           "maybe_record", "build_manifest"]

# Snapshot dir schema (pinned by tests): every snapshot contains exactly
# these entries, so downstream tooling can rely on the layout.
SNAPSHOT_FILES = ("manifest.json", "metrics.json", "events.jsonl",
                  "trace.json")
_SNAP_PREFIX = "snap-"


def build_manifest(reason: str, seq: Optional[int] = None) -> Dict[str, Any]:
    """The shared environment manifest (who/when/where/with-what-flags) every
    self-describing diagnostic artifact carries: flight-recorder snapshot
    dirs AND the profiling plane's per-run profile JSONs
    (:func:`autodist_tpu.telemetry.profiling.write_profile`) — so adprof can
    say whether two profiles even came from comparable runs."""
    import numpy as np
    flags = {k: os.environ[k] for k in sorted(const.KNOWN_FLAGS)  # graftlint: disable=GL007(the manifest dumps the RAW env value of every SET registered flag — a whole-registry diagnostic snapshot, not a typed single-flag read)
             if k in os.environ}
    manifest: Dict[str, Any] = {
        "reason": reason,
        "t_wall_s": round(time.time(), 3),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "process_id": const.ENV.AUTODIST_PROCESS_ID.val,
        "flags": flags,
        "versions": {"python": sys.version.split()[0],
                     "numpy": np.__version__},
    }
    if seq is not None:
        manifest["seq"] = seq
    try:
        import jax
        manifest["versions"]["jax"] = jax.__version__
    except Exception:   # jax-less diagnostics still snapshot
        pass
    try:
        # The applied execution plan (autotuner record: cache key + knobs +
        # predicted vs measured), when one was applied — a snapshot names
        # which plan the run it captured was executing.
        from autodist_tpu.telemetry import profiling as _profiling
        plan = _profiling.applied_plan()
        if plan:
            manifest["plan"] = plan
    except Exception:   # diagnostics must never fail the snapshot
        pass
    try:
        # Active alerts at capture time (the non-creating accessor: a
        # snapshot must not grow an alert engine as a side effect) — an
        # alert-triggered snapshot carries WHICH rule fired and its numbers.
        from autodist_tpu.telemetry import alerts as _alerts
        active = _alerts.active_alerts()
        if active:
            manifest["alerts"] = active
    except Exception:   # diagnostics must never fail the snapshot
        pass
    try:
        # Recovery actions so far (evictions/rejoins/rollbacks/respawns) —
        # an eviction- or rollback-triggered snapshot names what the
        # runtime already DID about the incident, not just what it saw.
        from autodist_tpu.parallel import recovery as _recovery
        rec = _recovery.recovery_snapshot()
        if any((rec.get("counts") or {}).values()):
            manifest["recovery"] = rec
    except Exception:   # diagnostics must never fail the snapshot
        pass
    try:
        # The memory plane's forensics record: owner census, per-program
        # ledger, recent device.mem history and the predicted-vs-live peak
        # delta — an OOM-triggered snapshot names the dominant owner from
        # the manifest alone. Present when the plane is armed (claims
        # exist or spans are on); a stable empty shell otherwise.
        from autodist_tpu.telemetry import memplane as _memplane
        manifest["memory"] = _memplane.memory_section()
    except Exception:   # diagnostics must never fail the snapshot
        pass
    return manifest


def _sanitize(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in reason)[:48] or "anomaly"


def _snap_seq(name: str) -> int:
    """The integer sequence number of a snapshot dir name, or -1 when the
    name does not parse (a foreign dir sorts first and evicts first)."""
    try:
        return int(name[len(_SNAP_PREFIX):].split("-", 1)[0])
    except ValueError:
        return -1


class FlightRecorder:
    """Bounded on-disk ring of telemetry snapshots.

    ``base_dir`` defaults to ``AUTODIST_RECORDER_DIR`` (falling back to
    ``<AUTODIST_WORKING_DIR>/flightrec``); ``keep`` and ``min_interval_s``
    default to ``AUTODIST_RECORDER_KEEP`` / ``AUTODIST_RECORDER_MIN_S``.
    :meth:`record` always captures; :meth:`maybe_record` (the automatic
    triggers' entry point) honors the debounce window. Thread-safe: the
    watchdog thread and the train loop may trigger concurrently — the lock
    covers only sequencing/debounce bookkeeping, never the file writes."""

    def __init__(self, base_dir: Optional[str] = None,
                 keep: Optional[int] = None,
                 min_interval_s: Optional[float] = None):
        env_dir = str(const.ENV.AUTODIST_RECORDER_DIR.val)
        self.base_dir = base_dir or env_dir \
            or os.path.join(const.DEFAULT_WORKING_DIR, "flightrec")
        self.keep = max(1, int(const.ENV.AUTODIST_RECORDER_KEEP.val
                               if keep is None else keep))
        self.min_interval_s = float(const.ENV.AUTODIST_RECORDER_MIN_S.val
                                    if min_interval_s is None
                                    else min_interval_s)
        self._lock = san_lock()
        self._last_record = -float("inf")
        self._seq = self._next_seq()

    def _next_seq(self) -> int:
        """Resume numbering past any snapshots already on disk, so a
        restarted process extends the ring instead of overwriting it."""
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return 0
        seqs = [_snap_seq(n) for n in names if n.startswith(_SNAP_PREFIX)]
        seqs = [s for s in seqs if s >= 0]
        return max(seqs) + 1 if seqs else 0

    def snapshots(self) -> list:
        """Snapshot dir paths on disk, oldest first (NUMERIC sequence order —
        a lexicographic sort would classify ``snap-10000`` as older than
        ``snap-9999`` and :meth:`_evict` would delete the newest snapshot the
        moment the counter grows a digit)."""
        try:
            names = [n for n in os.listdir(self.base_dir)
                     if n.startswith(_SNAP_PREFIX)]
        except OSError:
            return []
        return [os.path.join(self.base_dir, n)
                for n in sorted(names, key=lambda n: (_snap_seq(n), n))]

    def maybe_record(self, reason: str, server=None,
                     peers: Iterable = ()) -> Optional[str]:
        """The automatic-trigger entry point: capture unless the last
        snapshot is younger than ``min_interval_s`` (returns None then)."""
        return self._capture(reason, server, peers, debounced=True)

    def record(self, reason: str, server=None,
               peers: Iterable = ()) -> Optional[str]:
        """Capture one snapshot NOW (manual triggers bypass the debounce);
        returns the snapshot dir, or None when the write failed (a broken
        disk must not take down the run being diagnosed).

        ``server`` (a PSServer) contributes every worker ring deposited via
        ``push_trace``; ``peers`` are objects with a ``trace()`` method to
        pull live rings from. The local span ring is always lane 0."""
        return self._capture(reason, server, peers, debounced=False)

    def _capture(self, reason: str, server, peers,
                 debounced: bool) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            # Check AND claim the debounce window in ONE critical section:
            # the watchdog thread and the train loop's health boundary may
            # trigger within microseconds of each other, and both passing a
            # check-then-stamp-later window would write two snapshots.
            if debounced and now - self._last_record < self.min_interval_s:
                return None
            prev_last = self._last_record
            self._last_record = now
            seq = self._seq
            self._seq += 1
        # The process id is part of the dir name: multi-process runs share
        # the default base dir, and each process numbers its own sequence —
        # without the lane tag two processes would clobber one snap-NNNN
        # (the PR 5 host_spans_w<id> collision class).
        proc = int(const.ENV.AUTODIST_PROCESS_ID.val)
        path = os.path.join(
            self.base_dir,
            f"{_SNAP_PREFIX}{seq:04d}-w{proc}-{_sanitize(reason)}")
        try:
            os.makedirs(path, exist_ok=True)
            events = _metrics.events()
            self._write_manifest(path, reason, seq)
            with open(os.path.join(path, "metrics.json"), "w") as f:
                json.dump(_metrics.snapshot(), f, indent=1, default=str)
            _cluster.dump_events_jsonl(
                os.path.join(path, "events.jsonl"), events=events)
            self._write_trace(path, server, peers, events)
        except (OSError, ValueError, TypeError) as e:
            with self._lock:
                if self._last_record == now:   # no later capture claimed it
                    # Roll the debounce claim back: a transient write failure
                    # must not suppress the NEXT anomaly's snapshot for a
                    # whole min_interval_s window.
                    self._last_record = prev_last
            logging.warning("flight recorder: snapshot %r failed: %s",
                            reason, e)
            return None
        self._evict()
        logging.info("flight recorder: wrote snapshot %s (%s)", path, reason)
        return path

    def _write_manifest(self, path: str, reason: str, seq: int):
        manifest = build_manifest(reason, seq=seq)
        manifest["files"] = list(SNAPSHOT_FILES)
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)

    def _write_trace(self, path: str, server, peers, events):
        states = [_cluster.local_trace_state()]
        for peer in peers:
            try:
                states.append(peer.trace())
            except Exception as e:   # a dead peer must not sink the snapshot
                logging.debug("flight recorder: peer trace pull failed: %s", e)
        if server is not None:
            try:
                for _, st in sorted(server.worker_traces().items(),
                                    key=lambda kv: str(kv[0])):
                    states.append(st)
            except Exception as e:
                logging.debug("flight recorder: worker traces unavailable: "
                              "%s", e)
        _cluster.merge_trace_states(states, os.path.join(path, "trace.json"),
                                    instant_events=events)

    def _evict(self):
        snaps = self.snapshots()
        for old in snaps[:max(0, len(snaps) - self.keep)]:
            try:
                shutil.rmtree(old)
            except OSError as e:
                logging.debug("flight recorder: evicting %s failed: %s",
                              old, e)


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = san_lock()


def set_recorder(recorder: Optional[FlightRecorder]):
    """Install (or clear, with None) the process's flight recorder — the
    automatic triggers (watchdog, health monitors) record through it."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = recorder


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def get_or_create() -> FlightRecorder:
    """The installed recorder, or a fresh env-default one installed on the
    spot (the manual ``record`` opcode and ``action=record`` monitors must
    succeed without prior arming)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def maybe_record(reason: str, server=None,
                 peers: Iterable = ()) -> Optional[str]:
    """Automatic-trigger hook: record (debounced) through the installed
    recorder; with none installed, arm one only when ``AUTODIST_RECORDER``
    says so, else no-op. The un-armed cost is one global read + one env
    check — cheap enough for every watchdog tick and health boundary."""
    rec = _RECORDER
    if rec is None:
        if not const.ENV.AUTODIST_RECORDER.val:
            return None
        rec = get_or_create()
    return rec.maybe_record(reason, server=server, peers=peers)
