"""LSTM language model with sampled softmax — exact model-family parity with lm1b.

The reference's lm1b workload is an LSTM LM over a 793k-word vocabulary trained
with sampled softmax (``examples/lm1b/language_model.py:15-30``). The flagship
TPU workload here is the Transformer LM (``models/transformer_lm.py``), but this
module keeps the reference's exact model family available:

- The recurrence runs as a compiled ``lax.scan`` (via ``flax.linen.RNN`` over
  ``OptimizedLSTMCell``) — one fused cell matmul per step on the MXU, no Python
  per-timestep loop, static shapes throughout.
- Sampled softmax uses a **host-sampled static negative set** per batch
  (``neg_ids`` in the batch dict): TPU-friendly because the gather of sampled
  output-projection rows has a static shape, and the train step stays a pure
  function of (params, batch). The reference sampled candidates inside the graph
  with TF's log-uniform sampler; sampling on host keeps the step jittable and
  reproducible.
- The softmax weights are a separate (vocab, hidden) parameter, untied from the
  input embedding like the reference — both carry row-sparse gradients, which the
  Parallax strategy routes to PS (``parallax_strategy.py:24-71`` semantics).
"""

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LSTMLMConfig:
    vocab_size: int = 32000       # reference lm1b: 793_471
    emb_dim: int = 512
    hidden_dim: int = 1024
    n_layers: int = 2
    num_sampled: int = 1024       # sampled-softmax negatives per batch
    # Subtract log(expected sample probability) from sampled logits so the sampled
    # objective is an unbiased estimate of the full softmax under the log-uniform
    # sampler (TF sampled_softmax_loss's subtract_log_q=True default, which the
    # reference lm1b relies on). Disable only for diagnostics.
    subtract_log_q: bool = True
    dtype: Any = jnp.bfloat16


class LSTMLM(nn.Module):
    """Embedding → stacked LSTM → hidden states; the loss head lives in the loss fn
    so the sampled-softmax projection can gather only the rows it needs."""

    config: LSTMLMConfig

    @nn.compact
    def __call__(self, tokens, decode: bool = False):
        """``decode``: persist each layer's (c, h) carry in the ``cache``
        collection across apply() calls (run under ``mutable=["cache"]``), so
        autoregressive generation feeds one token at a time without re-running
        the prefix — the LSTM analogue of the Transformer's KV cache (state is
        O(hidden), not O(sequence))."""
        cfg = self.config
        x = nn.Embed(cfg.vocab_size, cfg.emb_dim, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(tokens)
        for i in range(cfg.n_layers):
            # nn.RNN lowers to lax.scan over the sequence axis; the cell's four
            # gates are one fused matmul per step.
            rnn = nn.RNN(nn.OptimizedLSTMCell(cfg.hidden_dim, dtype=cfg.dtype,
                                              param_dtype=jnp.float32),
                         name=f"lstm_{i}")
            if decode:
                zeros = lambda: (  # noqa: E731 — (c, h), the cell carry pair
                    jnp.zeros((x.shape[0], cfg.hidden_dim), cfg.dtype),
                    jnp.zeros((x.shape[0], cfg.hidden_dim), cfg.dtype))
                carry_var = self.variable("cache", f"carry_{i}", zeros)
                carry, x = rnn(x, initial_carry=carry_var.value,
                               return_carry=True)
                carry_var.value = carry
            else:
                x = rnn(x)
        return x  # [B, T, hidden]


class LSTMLMWithHead(nn.Module):
    """Wrapper owning the softmax projection so it lives in the same params tree."""

    config: LSTMLMConfig

    @nn.compact
    def __call__(self, tokens, decode: bool = False):
        cfg = self.config
        h = LSTMLM(cfg, name="lm")(tokens, decode=decode)
        # Parameters are declared here; the loss fn gathers rows out of them.
        self.param("softmax_w", nn.initializers.normal(0.02),
                   (cfg.vocab_size, cfg.hidden_dim), jnp.float32)
        self.param("softmax_b", nn.initializers.zeros, (cfg.vocab_size,),
                   jnp.float32)
        return h


def make_loss_fn(model: LSTMLMWithHead) -> Callable:
    """Sampled-softmax NLL.

    Batch dict: ``tokens`` int32 [B, L+1] (inputs/targets shifted internally) and
    optional ``neg_ids`` int32 [S] of host-sampled negative class ids. Without
    ``neg_ids`` the loss falls back to the full softmax (used for eval and for
    small-vocab tests).
    """
    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        # Sampled-softmax logit matmuls run in the model's compute dtype (the
        # [B,T,S,H] negatives einsum is the hot op; f32 would run it at a
        # fraction of the MXU rate); the softmax/logsumexp below is f32.
        h = model.apply({"params": params}, inputs)
        w = params["softmax_w"]            # [V, H]
        b = params["softmax_b"]            # [V]

        if "neg_ids" not in batch:
            # bf16 MXU inputs, f32 accumulate/output (preferred_element_type):
            # same rate, no bf16 rounding of the reduced logit.
            logits = jnp.matmul(h, w.T.astype(h.dtype),
                                preferred_element_type=jnp.float32) + b
            logprobs = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
            return nll.mean()

        neg_ids = batch["neg_ids"]         # [S], static length
        # True-class logit: gather one row per target (row-sparse grad on w).
        w_true = w[targets].astype(h.dtype)                   # [B, T, H]
        true_logit = jnp.einsum("bth,bth->bt", h, w_true,
                                preferred_element_type=jnp.float32) + b[targets]
        # Sampled negatives: one shared [S, H] gather for the whole batch.
        w_neg = w[neg_ids].astype(h.dtype)                    # [S, H]
        neg_logits = jnp.einsum("bth,sh->bts", h, w_neg,
                                preferred_element_type=jnp.float32) + b[neg_ids]
        if model.config.subtract_log_q:
            # Importance correction: logits -= log q(id) under the log-uniform
            # sampler q(id) = (log(id+2) - log(id+1)) / log(V+1). Applied to the
            # true class too (TF semantics); the shared log(V+1) and sample-count
            # terms are constant across classes and cancel in the softmax.
            def log_q(ids):
                idf = ids.astype(jnp.float32)
                return jnp.log(jnp.log1p(1.0 / (idf + 1.0))) - jnp.log(
                    jnp.log(float(model.config.vocab_size + 1)))

            true_logit = true_logit - log_q(targets)
            neg_logits = neg_logits - log_q(neg_ids)[None, None, :]
        # Mask accidental hits (a sampled id equal to the true target) so the
        # model is not penalized for assigning them probability (standard
        # sampled-softmax accidental-hit removal).
        hits = neg_ids[None, None, :] == targets[..., None]   # [B, T, S]
        neg_logits = jnp.where(hits, jnp.full_like(neg_logits, -1e9), neg_logits)
        # Softmax over [true | negatives]; NLL of the true class is position 0.
        all_logits = jnp.concatenate([true_logit[..., None], neg_logits], axis=-1)
        return (-true_logit + jax.nn.logsumexp(all_logits, axis=-1)).mean()

    return loss_fn


def make_fused_full_softmax_loss_fn(model: LSTMLMWithHead) -> Callable:
    """EXACT full-vocabulary softmax NLL via the pallas fused kernels.

    The reference could not train lm1b with the true softmax — at 793k words
    the logits tensor is tens of GiB, hence its sampled softmax
    (``language_model.py:15-30``). ``ops.fused_softmax_xent`` never
    materializes logits, so this loss trains the same model with the exact
    objective instead of the sampled approximation. Batch needs only
    ``tokens`` (no ``neg_ids``)."""

    def loss_fn(params, batch):
        from autodist_tpu.ops.fused_xent import fused_softmax_xent
        tokens = batch["tokens"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        h = model.apply({"params": params}, inputs)
        n = h.shape[0] * h.shape[1]
        h2 = h.reshape(n, h.shape[-1])
        # softmax_w stays in its stored [V, H] layout and f32 dtype — the kernel
        # contracts it as-is and casts per tile in VMEM, so no transposed or
        # downcast copy of the multi-GiB table is ever materialized.
        nll = fused_softmax_xent(h2, params["softmax_w"], targets.reshape(n),
                                 params["softmax_b"], w_layout="vd")
        return nll.mean()

    return loss_fn


def generate(model: LSTMLMWithHead, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Autoregressive generation: ``[B, P]`` int32 prompt ->
    ``[B, max_new_tokens]`` continuation, full-softmax head.

    Same shape as the Transformer's :func:`~autodist_tpu.models.
    transformer_lm.generate`: one prefill apply threads the whole prompt
    through the recurrence (the carry cache holds O(hidden) state — no
    sequence-length cache at all), then a single ``lax.scan`` of per-token
    steps. Works at the giant-vocab scale too: the per-step head is one
    ``[B, V]`` logits row, never a sequence of them."""
    from autodist_tpu.models.common import sample_logits
    if prompt.shape[1] < 1:
        raise ValueError("prompt must have at least one token")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def head(h_last):                       # [B, hidden] -> [B, V] f32
        w, b = params["softmax_w"], params["softmax_b"]
        return jnp.matmul(h_last, w.T.astype(h_last.dtype),
                          preferred_element_type=jnp.float32) + b

    h, variables = model.apply({"params": params}, prompt, decode=True,
                               mutable=["cache"])
    keys = jax.random.split(rng, max_new_tokens)
    first = sample_logits(head(h[:, -1]), keys[0], temperature, top_k, top_p)

    def step(carry, key):
        cache, tok = carry
        h, variables = model.apply({"params": params, "cache": cache},
                                   tok[:, None], decode=True,
                                   mutable=["cache"])
        nxt = sample_logits(head(h[:, 0]), key, temperature, top_k, top_p)
        return (variables["cache"], nxt), nxt

    if max_new_tokens == 1:
        return first[:, None]
    _, rest = jax.lax.scan(step, (variables["cache"], first), keys[1:])
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def make_generate_fn(model: LSTMLMWithHead, max_new_tokens: int,
                     temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 0.0) -> Callable:
    """``jit``-compiled ``f(params, prompt, rng=None)`` closing over the
    statics (one compile per prompt shape) — mirrors
    :func:`autodist_tpu.models.transformer_lm.make_generate_fn`."""
    def f(params, prompt, rng=None):
        return generate(model, params, prompt, max_new_tokens,
                        temperature=temperature, top_k=top_k,
                        top_p=top_p, rng=rng)
    return jax.jit(f)


def init_params(config: LSTMLMConfig, rng: Optional[jax.Array] = None,
                batch_size: int = 2):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = LSTMLMWithHead(config)
    tokens = jnp.zeros((batch_size, 8), jnp.int32)
    from autodist_tpu.models.common import jit_init
    return model, jit_init(model, tokens, rng=rng)


def synthetic_batch(config: LSTMLMConfig, batch_size: int, seq_len: int,
                    seed: int = 0, sampled: bool = True):
    rng = np.random.RandomState(seed)
    batch = {"tokens": rng.randint(0, config.vocab_size,
                                   size=(batch_size, seq_len + 1)).astype(np.int32)}
    if sampled:
        # Host-side log-uniform (Zipfian) candidate sampling, matching the
        # distribution TF's LogUniformCandidateSampler draws from.
        u = rng.uniform(size=(config.num_sampled,))
        ids = (np.exp(u * np.log(config.vocab_size + 1)) - 1).astype(np.int64)
        batch["neg_ids"] = np.clip(ids, 0, config.vocab_size - 1).astype(np.int32)
    return batch
