"""Shard the test suite across N pytest processes (suite wall-clock relief).

The suite's tail is dominated by the real multi-process cluster tests —
wall-clock there is process startup + coordination latency, not CPU, so
file-level sharding across a few pytest workers overlaps those waits with
the compile-heavy files. Measured on this image's single core: 41:31 serial
-> 35:00 at -n 4 (521 tests); on a multi-core host the win grows toward the
largest shard's runtime. No pytest-xdist in this image; this driver is the
dependency-free equivalent: greedy bin-packing of test FILES by size (a
cheap proxy for runtime) into N shards, one pytest subprocess each,
combined exit status.

    python tools/parallel_tests.py [-n 4] [-- extra pytest args]

File-level sharding is safe here because every test file is hermetic (own
tmp dirs, ephemeral ports, fresh AutoDist instances); two shards never share
a jax process.
"""

import argparse
import glob
import os
import subprocess
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from autodist_tpu.testing.sanitizer import san_lock  # noqa: E402


def shard_files(n: int):
    files = sorted(glob.glob(os.path.join(ROOT, "tests", "test_*.py")))
    if not files:
        raise SystemExit("no test files found")
    # Greedy: biggest file into the lightest shard. Size correlates with
    # runtime well enough; the multiprocess file dominates either way.
    files.sort(key=os.path.getsize, reverse=True)
    shards = [[] for _ in range(n)]
    weights = [0] * n
    for f in files:
        i = weights.index(min(weights))
        shards[i].append(f)
        weights[i] += os.path.getsize(f)
    return [s for s in shards if s]


def run_lint():
    """graftlint as a distinct pre-stage: static-analysis findings are NOT
    test failures — they fail with their own banner and exit code (2) so a
    red run is immediately attributable. Fast (<30s; pure AST)."""
    print("lint: graftlint (static analysis) ...")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "graftlint.py")],
        cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    if proc.returncode == 0:
        print(f"lint: OK ({proc.stdout.strip().splitlines()[-1]})")
        return True
    print("lint: FAILED — graftlint findings (static analysis, not test "
          "failures):")
    print(proc.stdout.rstrip())
    return False


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=4, help="shard count")
    parser.add_argument("--no-lint", action="store_true",
                        help="skip the graftlint pre-stage (ci.sh runs it "
                             "in its own lint stage)")
    parser.add_argument("rest", nargs="*", help="extra pytest args (after --)")
    args = parser.parse_args(argv)

    lint_ok = True if args.no_lint else run_lint()
    shards = shard_files(args.n)
    t0 = time.time()
    procs = []
    logs = []
    for i, shard in enumerate(shards):
        log = open(os.path.join(ROOT, f".pytest-shard-{i}.log"), "w")
        logs.append(log)
        cmd = [sys.executable, "-m", "pytest", "-q", *args.rest, *shard]
        procs.append(subprocess.Popen(cmd, cwd=ROOT, stdout=log,
                                      stderr=subprocess.STDOUT))
        print(f"shard {i}: {len(shard)} files "
              f"({', '.join(os.path.basename(f) for f in shard[:3])}...)")

    # One waiter thread per shard (sanitizer-factory lock around the shared
    # result map) so a finished shard reports immediately instead of behind
    # a slower earlier one. The finally is the teardown discipline the
    # thread-leak fence flagged: an interrupt used to abandon the remaining
    # shard PROCESSES and the waiters parked on them — now the children are
    # terminated and every waiter joined before main exits.
    results = {}
    results_lock = san_lock()

    def wait_one(i, p, log):
        rc = p.wait()
        log.close()
        with open(log.name) as f:
            tail = f.read().strip().splitlines()
        with results_lock:
            results[i] = (rc, tail, log.name)
        summary = tail[-1] if tail else "(no output)"
        print(f"shard {i}: rc={rc}  {summary}")

    waiters = [threading.Thread(target=wait_one, args=(i, p, log),
                                name=f"shard-waiter-{i}")
               for i, (p, log) in enumerate(zip(procs, logs))]
    try:
        for t in waiters:
            t.start()
        for t in waiters:
            t.join()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for t in waiters:
            if t.is_alive():
                t.join(timeout=30.0)

    failed = False
    for i in sorted(results):
        rc, tail, log_name = results[i]
        if rc != 0:
            failed = True
            print(f"--- shard {i} failures (see {log_name}) ---")
            print("\n".join(line for line in tail if "FAILED" in line
                            or "ERROR" in line) or "\n".join(tail[-15:]))
    print(f"total wall clock: {time.time() - t0:.0f}s across "
          f"{len(shards)} shards")
    if not lint_ok:
        print("lint: FAILED (graftlint — rerun: python tools/graftlint.py; "
              "distinct from the test results above)")
    if failed:
        return 1      # test failures (lint status printed separately)
    return 2 if not lint_ok else 0


if __name__ == "__main__":
    sys.exit(main())
