"""Masked-LM pretrain pipeline: text corpus -> BERT batches from disk.

Counterpart of the reference BERT benchmark's pretrain input
(``examples/benchmark/bert.py:82-98`` -> ``utils/input_pipeline.py``
``create_pretrain_dataset``: tfrecords with input_ids/segment_ids/
masked_lm_{positions,ids,weights} fields, masked OFFLINE by BERT's
create_pretraining_data). The TPU-first redesign splits that differently:

- **Prep** (:func:`prepare_mlm_shards`) streams a text corpus once and writes
  raw UNMASKED ``tokens-*.npy`` / ``token_types-*.npy`` shards — the same
  row-aligned files the native ``DataLoader(files=...)`` memory-maps. Rows are
  ``[CLS] words [SEP]`` (or ``[CLS] seg_a [SEP] seg_b [SEP]`` with
  ``segments=True``), padded to ``seq_len``.
- **Dynamic masking** (:class:`MLMBatcher`) draws a fresh 80/10/10 mask per
  batch on the host — every epoch sees different masks (static tfrecord
  masking shows the model one fixed mask forever; dynamic masking is the
  RoBERTa improvement and costs nothing here), deterministic under ``seed``.
  Output batches carry exactly the keys ``models/bert.py``'s
  ``make_mlm_loss_fn`` consumes: ``tokens, token_types, mlm_positions,
  mlm_targets, mlm_weights`` with a static ``max_predictions_per_seq`` slot
  count (the reference's fixed-slot layout — static shapes on TPU).

No next-sentence objective: the zoo's BERT has no NSP head (MLM-only, the
RoBERTa finding); ``segments=True`` still exercises the type-embedding path
the reference's segment_ids fed.

Special ids occupy the low range — ``pad=0`` (what the model's pad mask keys
on), ``cls=1``, ``sep=2``, ``mask=3`` — and corpus word ids are shifted up by
``N_SPECIAL``; the embedding must cover ``meta["vocab_size"]`` rows.
"""

import glob as globlib
import json
import os
from typing import Dict, Iterator, List, Optional

import numpy as np

from autodist_tpu.data.text_corpus import PathsSpec, Vocabulary, _resolve_paths, _words
from autodist_tpu.utils import logging

PAD_ID = 0
CLS_ID = 1
SEP_ID = 2
MASK_ID = 3
N_SPECIAL = 4

META_NAME = "mlm-meta.json"


def prepare_mlm_shards(files: PathsSpec, vocab: Vocabulary, directory: str,
                       seq_len: int, rows_per_shard: int = 1 << 15,
                       segments: bool = False, seed: int = 0) -> Dict[str, List[str]]:
    """Stream a corpus into raw MLM rows: ``tokens-*.npy`` + ``token_types-*.npy``.

    Each row packs ``seq_len - 2`` corpus words as ``[CLS] w.. [SEP]`` (types
    all 0); with ``segments=True``, ``seq_len - 3`` words split at a seeded
    random point into ``[CLS] a.. [SEP] b.. [SEP]`` with types 0/1 — the
    reference's segment_ids layout. Rows are full (no padding mid-corpus; the
    trailing partial row is dropped — static shapes). Word ids are shifted by
    ``N_SPECIAL``. Returns ``{"tokens": paths, "token_types": paths}`` and
    writes a ``mlm-meta.json`` sidecar the training side validates against.
    """
    if seq_len < (8 if segments else 4):
        raise ValueError(f"seq_len {seq_len} too short for the row layout")
    if rows_per_shard < 1:
        raise ValueError("rows_per_shard must be >= 1")
    os.makedirs(directory, exist_ok=True)
    for key in ("tokens", "token_types"):
        for stale in globlib.glob(os.path.join(globlib.escape(directory),
                                               f"{key}-*.npy")):
            os.remove(stale)

    n_words_row = seq_len - (3 if segments else 2)
    rng = np.random.RandomState(seed)
    tok_buf = np.empty((rows_per_shard, seq_len), np.int32)
    typ_buf = np.zeros((rows_per_shard, seq_len), np.int32)
    n_buf = 0
    n_rows = 0
    paths: Dict[str, List[str]] = {"tokens": [], "token_types": []}
    row_words: List[int] = []

    def flush():
        nonlocal n_buf
        if n_buf == 0:
            return
        for key, buf in (("tokens", tok_buf), ("token_types", typ_buf)):
            path = os.path.join(directory, f"{key}-{len(paths[key]):05d}.npy")
            np.save(path, buf[:n_buf])
            paths[key].append(path)
        n_buf = 0

    for word in _words(_resolve_paths(files)):
        row_words.append(N_SPECIAL + vocab.lookup(word))
        if len(row_words) < n_words_row:
            continue
        row = tok_buf[n_buf]
        types = typ_buf[n_buf]
        types[:] = 0
        if segments:
            # Split point away from the edges so both segments are real.
            lo = max(1, n_words_row // 4)
            split = int(rng.randint(lo, n_words_row - lo + 1))
            row[0] = CLS_ID
            row[1:1 + split] = row_words[:split]
            row[1 + split] = SEP_ID
            row[2 + split:2 + n_words_row] = row_words[split:]
            row[2 + n_words_row] = SEP_ID
            types[2 + split:] = 1
        else:
            row[0] = CLS_ID
            row[1:1 + n_words_row] = row_words
            row[1 + n_words_row] = SEP_ID
        row_words.clear()
        n_buf += 1
        n_rows += 1
        if n_buf == rows_per_shard:
            flush()
    flush()
    if not paths["tokens"]:
        raise ValueError(
            f"corpus has fewer than {n_words_row} words; no MLM rows")

    vocab_size = N_SPECIAL + vocab.vocab_size
    with open(os.path.join(directory, META_NAME), "w") as f:
        json.dump({"vocab_size": vocab_size, "seq_len": seq_len,
                   "rows": n_rows, "segments": segments,
                   "n_special": N_SPECIAL, "mask_id": MASK_ID,
                   "oov_buckets": vocab.oov_buckets}, f, indent=1)
    logging.info("Prepared %d MLM rows of len %d (segments=%s) across %d "
                 "shards in %s (vocab %d incl. %d specials)", n_rows, seq_len,
                 segments, len(paths["tokens"]), directory, vocab_size,
                 N_SPECIAL)
    return paths


def read_meta(directory: str) -> Optional[dict]:
    path = os.path.join(directory, META_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def open_mlm_loader(directory: str, batch_size: int, **loader_kw):
    """DataLoader over a prepared MLM shard directory (+ its meta) — the
    single place shard discovery lives (escaped glob: a directory named
    ``runs[2026]`` must not silently match nothing)."""
    from autodist_tpu.data.loader import DataLoader
    meta = read_meta(directory)
    if meta is None:
        raise FileNotFoundError(f"no {META_NAME} under {directory!r} "
                                f"(prepare_mlm_shards writes one)")
    files = {k: sorted(globlib.glob(os.path.join(globlib.escape(directory),
                                                 f"{k}-*.npy")))
             for k in ("tokens", "token_types")}
    return DataLoader(files=files, batch_size=batch_size, **loader_kw), meta


def mask_batch(tokens: np.ndarray, rng: np.random.Generator, *,
               vocab_size: int, max_predictions: int,
               mask_prob: float = 0.15) -> Dict[str, np.ndarray]:
    """One dynamic-masking draw over a raw ``[B, L]`` token batch.

    BERT's 80/10/10 recipe with the reference's fixed-slot layout: per row,
    ``min(max_predictions, round(mask_prob * n_maskable))`` positions are
    drawn without replacement among non-special tokens; 80% become
    ``MASK_ID``, 10% a uniform random word id, 10% stay. Unused slots carry
    weight 0 (and position 0, which the loss ignores through the weight).
    Returns ``{"tokens", "mlm_positions", "mlm_targets", "mlm_weights"}``
    with ``tokens`` a masked COPY of the input.
    """
    if not 0.0 < mask_prob <= 1.0:
        raise ValueError(f"mask_prob {mask_prob} out of (0, 1]")
    batch, length = tokens.shape
    P = max_predictions
    maskable = tokens >= N_SPECIAL
    # Rank positions by a random key, non-maskable pushed to the end: the
    # first k columns of the argsort are a uniform sample w/o replacement.
    keys = rng.random((batch, length))
    keys[~maskable] = np.inf
    order = np.argsort(keys, axis=1)[:, :P].astype(np.int32)    # [B, P]
    n_maskable = maskable.sum(axis=1)
    k = np.minimum(np.maximum(np.rint(mask_prob * n_maskable), 1), P)
    k = np.minimum(k, n_maskable).astype(np.int32)              # rows can be all-pad
    slot = np.arange(P)[None, :]
    weights = (slot < k[:, None]).astype(np.float32)            # [B, P]
    positions = np.where(weights > 0, order, 0).astype(np.int32)

    rows = np.arange(batch)[:, None]
    targets = tokens[rows, positions].astype(np.int32)
    u = rng.random((batch, P))
    replacement = np.where(
        u < 0.8, MASK_ID,
        np.where(u < 0.9,
                 rng.integers(N_SPECIAL, vocab_size, size=(batch, P)),
                 targets)).astype(tokens.dtype)
    masked = tokens.copy()
    live = weights > 0
    # Dead slots write the original value back at position 0 — a no-op, so no
    # scatter mask is needed.
    masked[rows, positions] = np.where(live, replacement, targets)
    return {"tokens": masked, "mlm_positions": positions,
            "mlm_targets": targets, "mlm_weights": weights}


class MLMBatcher:
    """Dynamic-masking view over a :class:`~autodist_tpu.data.DataLoader`.

    Wraps a loader serving raw ``{"tokens", "token_types"}`` batches (the
    :func:`prepare_mlm_shards` files) and yields full MLM batches. Masking is
    deterministic under ``seed`` given the loader's batch order (the loader's
    own shuffle is seeded too, so a fixed (loader seed, batcher seed) pair
    replays an identical stream — the property the determinism test pins).
    """

    def __init__(self, loader, *, vocab_size: int, max_predictions: int = 20,
                 mask_prob: float = 0.15, seed: int = 0):
        self._loader = loader
        self.vocab_size = vocab_size
        self.max_predictions = max_predictions
        self.mask_prob = mask_prob
        self._rng = np.random.Generator(np.random.PCG64(seed))

    def next(self) -> Dict[str, np.ndarray]:
        raw = self._loader.next()
        out = mask_batch(raw["tokens"], self._rng, vocab_size=self.vocab_size,
                         max_predictions=self.max_predictions,
                         mask_prob=self.mask_prob)
        out["token_types"] = raw.get(
            "token_types", np.zeros_like(raw["tokens"]))
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next()
