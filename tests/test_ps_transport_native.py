"""Native C++ framed-transport data plane vs the Python fallback.

Both speak the identical framing (8-byte big-endian length + payload), so any
mix of endpoints interoperates; these tests drive every pairing over a real
socketpair with multi-MB tensor payloads.
"""

import pickle
import socket
import struct
import threading

import numpy as np
import pytest

from autodist_tpu.parallel import ps_transport as tp


def _python_send(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.Struct("!Q").pack(len(payload)) + payload)


def _python_recv(sock):
    hdr = struct.Struct("!Q")
    (n,) = hdr.unpack(tp._recv_exact(sock, hdr.size))
    return pickle.loads(tp._recv_exact(sock, n))


def _payloads():
    rng = np.random.RandomState(0)
    return [
        {"grads": {"w": rng.randn(512, 513).astype(np.float32)},
         "version": 7, "worker": 1},
        ("pull", 3),
        {"big": rng.randn(1 << 21).astype(np.float32)},   # 8 MB
        b"",
    ]


def _roundtrip(send_fn, recv_fn):
    a, b = socket.socketpair()
    try:
        results = []
        def reader():
            for _ in range(len(_payloads())):
                results.append(recv_fn(b))
        t = threading.Thread(target=reader)
        t.start()
        for msg in _payloads():
            send_fn(a, msg)
        t.join(timeout=30)
        assert not t.is_alive()
        return results
    finally:
        a.close()
        b.close()


def _check(results):
    expected = _payloads()
    assert len(results) == len(expected)
    np.testing.assert_array_equal(results[0]["grads"]["w"],
                                  expected[0]["grads"]["w"])
    assert results[0]["version"] == 7
    assert results[1] == ("pull", 3)
    np.testing.assert_array_equal(results[2]["big"], expected[2]["big"])
    assert results[3] == b""


def test_python_fallback_roundtrip():
    _check(_roundtrip(_python_send, _python_recv))


@pytest.mark.skipif(tp._native_transport() is None,
                    reason="native transport unavailable (no g++)")
@pytest.mark.parametrize("pairing", ["native<->native", "native->python",
                                     "python->native"])
def test_native_and_mixed_roundtrips(pairing):
    send_fn = tp._send_msg if pairing != "python->native" else _python_send
    recv_fn = (lambda s: tp._recv_msg(s)[0]) if pairing != "native->python" \
        else _python_recv
    # _send_msg/_recv_msg route to the native lib (sockets are blocking here).
    _check(_roundtrip(send_fn, recv_fn))


@pytest.mark.skipif(tp._native_transport() is None,
                    reason="native transport unavailable (no g++)")
def test_timeout_sockets_use_python_path():
    """A socket with a timeout must keep Python timeout semantics (native raw
    -fd syscalls would bypass them), and still interoperate."""
    a, b = socket.socketpair()
    try:
        b.settimeout(30.0)
        tp._send_msg(a, {"x": 1})              # native (blocking side)
        assert tp._recv_msg(b)[0] == {"x": 1}  # python (timeout side)
        with pytest.raises(socket.timeout):
            b.settimeout(0.2)
            tp._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_peer_close_raises_connection_error():
    a, b = socket.socketpair()
    a.close()
    try:
        with pytest.raises(ConnectionError):
            tp._recv_msg(b)
    finally:
        b.close()
