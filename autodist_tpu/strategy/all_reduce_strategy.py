"""AllReduce strategy: every parameter synchronized by gradient all-reduce.

Port of reference ``autodist/strategy/all_reduce_strategy.py``: all variables get an
AllReduceSynchronizer; ``chunk_size`` maps the i-th parameter to collective fusion
group ``i // chunk_size`` (``:61-67`` — there for ScopedAllocator merging, here an XLA
all-reduce combiner hint); ``spec`` and ``compressor`` knobs preserved (``:71-90``)
with NCCL/RING re-interpreted as ICI/DCN network tiers.
"""

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import AR_DEFAULT_AXES, Strategy, StrategyBuilder

_SPECS = {
    "AUTO": strategy_pb2.AllReduceSynchronizer.AUTO,
    "ICI": strategy_pb2.AllReduceSynchronizer.ICI,
    "DCN": strategy_pb2.AllReduceSynchronizer.DCN,
    # Reference spellings accepted for compatibility (NCCL ~ fast intra-tier,
    # RING ~ generic cross-tier).
    "NCCL": strategy_pb2.AllReduceSynchronizer.ICI,
    "RING": strategy_pb2.AllReduceSynchronizer.DCN,
}

_COMPRESSORS = {
    "NoneCompressor": strategy_pb2.AllReduceSynchronizer.NONE,
    "HorovodCompressor": strategy_pb2.AllReduceSynchronizer.BF16,
    "HorovodCompressorEF": strategy_pb2.AllReduceSynchronizer.BF16_EF,
    # The reference drafted PowerSGDCompressor but shipped it disabled
    # (compressor.py:208-284); here it is implemented (parallel/synchronization.py).
    "PowerSGDCompressor": strategy_pb2.AllReduceSynchronizer.POWER_SGD,
    # TPU-native spellings.
    "none": strategy_pb2.AllReduceSynchronizer.NONE,
    "bf16": strategy_pb2.AllReduceSynchronizer.BF16,
    "bf16_ef": strategy_pb2.AllReduceSynchronizer.BF16_EF,
    "power_sgd": strategy_pb2.AllReduceSynchronizer.POWER_SGD,
}


def parse_ar_options(chunk_size: int, all_reduce_spec: str, compressor: str):
    """Validate AllReduce knobs; shared by every builder that emits AR synchronizers."""
    if chunk_size < 1:
        raise ValueError("chunk_size must be >= 1")
    if all_reduce_spec not in _SPECS:
        raise ValueError(f"Unknown all_reduce_spec {all_reduce_spec!r}; valid: {sorted(_SPECS)}")
    if compressor not in _COMPRESSORS:
        raise ValueError(f"Unknown compressor {compressor!r}; valid: {sorted(_COMPRESSORS)}")
    return chunk_size, _SPECS[all_reduce_spec], _COMPRESSORS[compressor]


def fill_ar_synchronizer(node, *, spec: int, compressor: int, group: int,
                         power_sgd_rank: int = 2):
    """Fill one node's AllReduceSynchronizer — the single emission point, so a new
    proto field propagates to every builder that emits AR nodes."""
    ar = node.all_reduce_synchronizer
    ar.spec = spec
    ar.compressor = compressor
    if compressor == strategy_pb2.AllReduceSynchronizer.POWER_SGD:
        ar.power_sgd_rank = power_sgd_rank
    ar.group = group


def fill_ar_node_configs(strategy: Strategy, model_spec: ModelSpec, *, spec: int,
                         compressor: int, chunk_size: int, power_sgd_rank: int = 2):
    """Emit one AllReduceSynchronizer node per trainable parameter — the shared
    emission for every replicated-parameter builder (AllReduce, SequenceParallel)."""
    for i, pspec in enumerate(model_spec.trainable.values()):
        node = strategy.proto.node_config.add(var_name=pspec.name)
        node.sparse = pspec.sparse
        fill_ar_synchronizer(node, spec=spec, compressor=compressor,
                             group=i // chunk_size, power_sgd_rank=power_sgd_rank)


class AllReduce(StrategyBuilder):
    def __init__(self, chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor", power_sgd_rank: int = 2):
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)
        if power_sgd_rank < 1:
            raise ValueError("power_sgd_rank must be >= 1")
        self._power_sgd_rank = power_sgd_rank

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        fill_ar_node_configs(strategy, model_spec, spec=self._spec,
                             compressor=self._compressor,
                             chunk_size=self._chunk_size,
                             power_sgd_rank=self._power_sgd_rank)
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, AR_DEFAULT_AXES))
        return strategy
