"""graftlint engine: parsing, directives, check registry, baseline, output.

Design notes:

- One :class:`Module` per source file: the ast tree, the raw lines, and every
  ``# graftlint:`` directive found by a ``tokenize`` pass (comments are not in
  the AST). Checks receive the Module plus a repo-level :class:`Context` and
  return :class:`Finding` lists; the engine applies suppressions and the
  baseline afterwards so checks stay oblivious to both.
- Finding fingerprints are line-number-free — ``check|path|scope|message`` —
  so a committed baseline survives unrelated edits above a grandfathered
  finding. ``scope`` is the enclosing def/class qualname.
- GL000 is the analyzer's own meta-check (malformed directives, reasonless
  suppressions, unparseable files). GL000 findings cannot be suppressed —
  otherwise a typo'd suppression could silence the report about itself.
"""

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

META_CHECK = "GL000"
_CHECK_ID_RE = re.compile(r"^GL\d{3}$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``scope`` + ``message`` (not line) key the baseline."""

    check: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    scope: str = ""    # enclosing def/class qualname ("" = module level)

    @property
    def fingerprint(self) -> str:
        return f"{self.check}|{self.path}|{self.scope}|{self.message}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.check} {self.message}{scope}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]            # new findings (post-suppress, post-baseline)
    suppressed: List[Tuple[Finding, str]]   # (finding, reason)
    baselined: List[Finding]
    stale_baseline: List[str]          # fingerprints no longer produced
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


class Check:
    """Registry entry: id, one-line title, the check fn, and --explain docs."""

    def __init__(self, check_id: str, title: str, fn: Callable, doc: str):
        self.id = check_id
        self.title = title
        self.fn = fn
        self.doc = doc or ""


_CHECKS: Dict[str, Check] = {}


def register(check_id: str, title: str):
    """Decorator registering ``fn(module, ctx) -> [Finding]`` under ``GLxxx``."""
    if not _CHECK_ID_RE.match(check_id):
        raise ValueError(f"check id must match GLnnn, got {check_id!r}")

    def deco(fn):
        if check_id in _CHECKS:
            raise ValueError(f"duplicate check id {check_id}")
        _CHECKS[check_id] = Check(check_id, title, fn, fn.__doc__)
        return fn

    return deco


def all_checks() -> Dict[str, Check]:
    """The registry, with the built-in check modules imported."""
    from autodist_tpu.analysis import checks  # noqa: F401  (side effect: registration)
    return dict(_CHECKS)


# ------------------------------------------------------------------ directives

_DIRECTIVE_RE = re.compile(r"#\s*graftlint\s*:\s*(.+?)\s*$")
_DISABLE_ENTRY_RE = re.compile(r"(GL\d{3})\s*(\(([^()]*)\))?")
_LOCK_ORDER_RE = re.compile(
    r"lock-order\s*=\s*([A-Za-z_][\w]*)\s*->\s*([A-Za-z_][\w]*)")


class Module:
    """One parsed source file plus its graftlint directives."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[Finding] = None
        # line -> {check_id: reason}
        self.suppressions: Dict[int, Dict[str, str]] = {}
        self.lock_orders: List[Tuple[str, str]] = []
        self.directive_findings: List[Finding] = []
        self._scopes: Optional[List[Tuple[int, int, str]]] = None
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            self.parse_error = Finding(
                META_CHECK, self.relpath, e.lineno or 1, e.offset or 0,
                f"file does not parse: {e.msg}")
        self._scan_directives()

    # -- directives ---------------------------------------------------------
    def _scan_directives(self):
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return  # the parse_error finding already covers it
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DIRECTIVE_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            standalone = not self.lines[line - 1][:tok.start[1]].strip()
            target = self._next_code_line(line + 1) if standalone else line
            self._parse_directive(m.group(1), line, target)

    def _next_code_line(self, start: int) -> int:
        for i in range(start, len(self.lines) + 1):
            text = self.lines[i - 1].strip()
            if text and not text.startswith("#"):
                return i
        return start

    def _parse_directive(self, body: str, line: int, target: int):
        recognized = False
        if "disable" in body:
            recognized = True
            # Everything after "disable=" is the entry list.
            _, _, entries = body.partition("disable")
            entries = entries.lstrip("= ")
            matched_any = False
            for m in _DISABLE_ENTRY_RE.finditer(entries):
                matched_any = True
                check_id, reason = m.group(1), (m.group(3) or "").strip()
                if not reason:
                    self.directive_findings.append(Finding(
                        META_CHECK, self.relpath, line, 0,
                        f"suppression of {check_id} has no reason; write "
                        f"`# graftlint: disable={check_id}(why it is safe)`"))
                    continue
                if check_id == META_CHECK:
                    self.directive_findings.append(Finding(
                        META_CHECK, self.relpath, line, 0,
                        "GL000 (analyzer meta findings) cannot be suppressed"))
                    continue
                self.suppressions.setdefault(target, {})[check_id] = reason
            if not matched_any:
                self.directive_findings.append(Finding(
                    META_CHECK, self.relpath, line, 0,
                    f"malformed disable directive {body!r}; expected "
                    f"`disable=GLnnn(reason)`"))
        for m in _LOCK_ORDER_RE.finditer(body):
            recognized = True
            self.lock_orders.append((m.group(1), m.group(2)))
        if not recognized:
            self.directive_findings.append(Finding(
                META_CHECK, self.relpath, line, 0,
                f"unrecognized graftlint directive {body!r} (known: "
                f"disable=GLnnn(reason), lock-order=a->b)"))

    def suppression_for(self, finding: Finding) -> Optional[str]:
        """The reason suppressing ``finding``, or None. A directive applies to
        its own line (trailing comment) or, standalone, to the next code line."""
        if finding.check == META_CHECK:
            return None
        by_line = self.suppressions.get(finding.line)
        if by_line and finding.check in by_line:
            return by_line[finding.check]
        return None

    # -- scopes -------------------------------------------------------------
    def scope_at(self, node_or_line) -> str:
        """Innermost enclosing def/class qualname for a node or line number."""
        line = getattr(node_or_line, "lineno", node_or_line)
        if self._scopes is None:
            self._scopes = []
            if self.tree is not None:
                self._collect_scopes(self.tree, "")
        best = ""
        best_span = None
        for start, end, name in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = name, span
        return best

    def _collect_scopes(self, node, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                self._scopes.append(
                    (child.lineno, child.end_lineno or child.lineno, qual))
                self._collect_scopes(child, qual)
            else:
                self._collect_scopes(child, prefix)


class Context:
    """Repo-level facts shared across modules (const.py flag registry,
    pyproject markers). Lazily computed, overridable for fixture tests."""

    def __init__(self, root: str, known_flags: Optional[Set[str]] = None):
        self.root = root
        self._known_flags = known_flags
        self._pyproject_markers: Optional[Set[str]] = None

    def known_flags(self) -> Optional[Set[str]]:
        """AUTODIST_* names registered in const.py's KNOWN_FLAGS (falling back
        to _ENV_DEFAULTS keys); None when const.py is absent (fixture trees),
        which disables the unknown-flag rule rather than flagging everything."""
        if self._known_flags is not None:
            return self._known_flags
        const_path = os.path.join(self.root, "autodist_tpu", "const.py")
        if not os.path.isfile(const_path):
            return None
        flags: Set[str] = set()
        try:
            with open(const_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) \
                        and target.id in ("KNOWN_FLAGS", "_ENV_DEFAULTS") \
                        and isinstance(node.value, ast.Dict):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) \
                                and isinstance(key.value, str):
                            flags.add(key.value)
        self._known_flags = flags or None
        return self._known_flags

    def pyproject_markers(self) -> Set[str]:
        """Marker names registered under [tool.pytest.ini_options] markers."""
        if self._pyproject_markers is not None:
            return self._pyproject_markers
        markers: Set[str] = set()
        path = os.path.join(self.root, "pyproject.toml")
        if os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                text = ""
            # A full TOML parse is overkill for one list of "name: help" strings.
            for m in re.finditer(r'"([A-Za-z_][\w]*)\s*:', text):
                markers.add(m.group(1))
        self._pyproject_markers = markers
        return markers


# -------------------------------------------------------------------- baseline

def load_baseline(path: str) -> Set[str]:
    """Fingerprints grandfathered by the committed baseline file."""
    if not path or not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]):
    """Rewrite the baseline from the current findings (sorted, stable diffs).
    GL000 meta-findings (malformed directives etc.) are never written: they
    must be fixed, not grandfathered — the baseline matcher ignores them
    anyway (see :func:`lint_paths`)."""
    entries = sorted(
        ({"fingerprint": f.fingerprint, "note": f.render()}
         for f in findings if f.check != META_CHECK),
        key=lambda e: e["fingerprint"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "graftlint grandfathered findings; new findings "
                              "fail CI, these do not. Regenerate with "
                              "tools/graftlint.py --write-baseline.",
                   "findings": entries}, f, indent=1)
        f.write("\n")


# ------------------------------------------------------------------ file walks

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", "native"}


def iter_py_files(paths: Sequence[str], root: str):
    """Yield .py files under ``paths`` (files taken verbatim, dirs walked).
    A nonexistent path raises: a CI gate that silently lints 0 files on a
    typo'd/renamed path would green-light everything it exists to block."""
    seen = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            raise FileNotFoundError(f"graftlint: path does not exist: {p}")
        if os.path.isfile(full):
            if full not in seen:
                seen.add(full)
                yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    f = os.path.join(dirpath, name)
                    if f not in seen:
                        seen.add(f)
                        yield f


# ---------------------------------------------------------------------- driver

def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               baseline: Optional[Set[str]] = None,
               checks: Optional[Sequence[str]] = None,
               context: Optional[Context] = None) -> LintResult:
    """Run the registry over ``paths``; returns the triaged result.

    ``baseline`` is a fingerprint set (see :func:`load_baseline`); matching
    findings are reported separately and do not fail the run. ``checks``
    restricts to a subset of check ids (fixture tests)."""
    root = os.path.abspath(root or os.getcwd())
    ctx = context or Context(root)
    registry = all_checks()
    selected = [registry[c] for c in checks] if checks \
        else list(registry.values())
    baseline = baseline or set()

    raw: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    files = 0
    for path in iter_py_files(paths, root):
        files += 1
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            raw.append(Finding(META_CHECK, rel.replace(os.sep, "/"), 1, 0,
                               f"unreadable file: {e}"))
            continue
        mod = Module(path, rel, source)
        raw.extend(mod.directive_findings)
        if mod.parse_error is not None:
            raw.append(mod.parse_error)
            continue
        for check in selected:
            for finding in check.fn(mod, ctx):
                reason = mod.suppression_for(finding)
                if reason is not None:
                    suppressed.append((finding, reason))
                else:
                    raw.append(finding)

    # GL000 never matches the baseline: grandfathering a malformed/reasonless
    # directive would defeat the "GL000 cannot be suppressed" invariant
    # through the --write-baseline side door.
    new = [f for f in raw
           if f.check == META_CHECK or f.fingerprint not in baseline]
    grandfathered = [f for f in raw
                     if f.check != META_CHECK and f.fingerprint in baseline]
    stale = sorted(baseline - {f.fingerprint for f in raw})
    order = lambda f: (f.path, f.line, f.col, f.check)  # noqa: E731
    return LintResult(findings=sorted(new, key=order),
                      suppressed=suppressed,
                      baselined=sorted(grandfathered, key=order),
                      stale_baseline=stale,
                      files_checked=files)
