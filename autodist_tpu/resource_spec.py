"""Resource specification: the cluster description the user hands to AutoDist.

Capability parity with reference ``autodist/resource_spec.py:45-331``:

- YAML schema ``nodes:`` (address / chief / accelerators / cpus / ssh_config /
  network_bandwidth, bandwidth defaulting to 1 GBE as in reference ``:209-215``) and
  ``ssh:`` config groups (username / key_file / port / python_venv / shared_envs,
  reference ``:291-331``).
- ``DeviceSpec`` with the string form ``address:TYPE:index`` (reference ``:241-265``
  used ``ip:GPU:0``); here TPU is a first-class device type.
- Chief rules: exactly one chief; a single-node spec is implicitly chief (reference
  ``:100-138`` via cluster, surfaced here).

TPU-native extension: a node may declare ``tpus: <count>`` and the spec may carry a
``mesh:`` section naming logical axis sizes (``data`` / ``reduce`` / ``model`` / ``seq`` /
``expert`` / ``pipe``). The mesh section is consumed by
:func:`autodist_tpu.parallel.mesh.build_mesh`.
"""

import copy
import enum
import os
from typing import Dict, List, Optional, Tuple

import yaml

# Default bandwidth in Gbps when a node does not declare one — reference
# resource_spec.py:209-215 defaults to 1 GBE.
DEFAULT_NETWORK_BANDWIDTH_GBPS = 1


class DeviceType(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class Connectivity(enum.Enum):
    """Relative closeness of two devices (reference resource_spec.py Connectivity)."""

    ETHERNET = 0     # cross-host over DCN/ethernet
    SAME_HOST = 1    # same host, different chips (PCIe on GPU; ICI on TPU slice)
    SAME_DEVICE = 2


class DeviceSpec:
    """One physical device, addressable as ``host:TYPE:index``.

    Reference parity: ``resource_spec.py:241-265`` (``ip:GPU:0`` string round-trip,
    tested by reference ``tests/test_device_spec.py:11-20``).
    """

    def __init__(self, host: str, device_type: DeviceType = DeviceType.CPU,
                 device_index: int = 0):
        self.host = host
        self.device_type = device_type
        self.device_index = device_index

    @property
    def name_string(self) -> str:
        if self.device_type is DeviceType.CPU:
            return self.host
        return f"{self.host}:{self.device_type.name}:{self.device_index}"

    @classmethod
    def from_string(cls, name: str) -> "DeviceSpec":
        parts = name.split(":")
        if len(parts) == 1:
            return cls(parts[0], DeviceType.CPU, 0)
        if len(parts) == 3:
            return cls(parts[0], DeviceType[parts[1].upper()], int(parts[2]))
        raise ValueError(f"Malformed device string: {name!r}")

    def connectivity_with(self, other: "DeviceSpec") -> Connectivity:
        if self.host != other.host:
            return Connectivity.ETHERNET
        if (self.device_type, self.device_index) == (other.device_type, other.device_index):
            return Connectivity.SAME_DEVICE
        return Connectivity.SAME_HOST

    def __repr__(self):
        return f"DeviceSpec({self.name_string})"

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and self.name_string == other.name_string

    def __hash__(self):
        return hash(self.name_string)


class SSHConfig:
    """One ssh group entry (reference resource_spec.py:280-306)."""

    def __init__(self, name: str, conf: dict):
        self.name = name
        self.username = conf.get("username", "")
        self.port = int(conf.get("port", 22))
        self.python_venv = conf.get("python_venv", "")
        self.key_file = conf.get("key_file", "")
        self.shared_envs = dict(conf.get("shared_envs", {}))


class SSHConfigMap(dict):
    """name -> SSHConfig (reference resource_spec.py:309-331)."""

    def __init__(self, conf: Optional[dict] = None):
        super().__init__()
        for name, c in (conf or {}).items():
            self[name] = SSHConfig(name, c)


class Node:
    """One host entry from the ``nodes:`` list."""

    def __init__(self, entry: dict):
        if "address" not in entry:
            raise ValueError("Every node needs an 'address'")
        self.address: str = str(entry["address"])
        self.chief: bool = bool(entry.get("chief", False))
        self.ssh_config_name: Optional[str] = entry.get("ssh_config")
        # Whether the spec stated a bandwidth (vs the 1 GBE default): consumers
        # making numerics-affecting choices (AutoStrategy's lossy wire codecs)
        # must not treat the defaulted value as a measurement.
        self.bandwidth_specified: bool = "network_bandwidth" in entry
        self.network_bandwidth: int = int(
            entry.get("network_bandwidth", DEFAULT_NETWORK_BANDWIDTH_GBPS))
        if self.network_bandwidth <= 0:
            raise ValueError(f"network_bandwidth must be positive on node {self.address}")
        # Accelerators. `tpus: N` is the TPU-native form; `gpus: [i,...]` is accepted for
        # schema compat with reference specs and treated as generic accelerator indices.
        self.tpu_indices: List[int] = list(range(int(entry.get("tpus", 0))))
        self.gpu_indices: List[int] = [int(i) for i in entry.get("gpus", [])]
        self.cpu_indices: List[int] = [int(i) for i in entry.get("cpus", [])] or [0]

    @property
    def accelerator_devices(self) -> List[DeviceSpec]:
        devs = [DeviceSpec(self.address, DeviceType.TPU, i) for i in self.tpu_indices]
        devs += [DeviceSpec(self.address, DeviceType.GPU, i) for i in self.gpu_indices]
        return devs

    @property
    def cpu_devices(self) -> List[DeviceSpec]:
        return [DeviceSpec(self.address, DeviceType.CPU, i) for i in self.cpu_indices]


class ResourceSpec:
    """Parsed resource spec.

    Accepts a YAML file path, a YAML string, or a pre-parsed dict. With no argument,
    builds a single-host spec from the locally visible JAX device count (the
    "fake-cluster"/single-node mode used by tests; reference single-node specs are
    ``tests/integration/resource_specs/r0.yml``).
    """

    def __init__(self, resource_file: Optional[str] = None, *, resource_info: Optional[dict] = None):
        if resource_info is not None:
            info = copy.deepcopy(resource_info)
        elif resource_file is None:
            info = self._local_default_info()
        elif os.path.exists(resource_file):
            with open(resource_file) as f:
                info = yaml.safe_load(f) or {}
        else:
            # Allow passing inline YAML text.
            info = yaml.safe_load(resource_file)
            if not isinstance(info, dict):
                raise FileNotFoundError(f"No such resource spec file: {resource_file}")

        if not isinstance(info, dict):
            raise ValueError(f"Resource spec must be a YAML mapping, got {type(info).__name__}")
        nodes_conf = info.get("nodes") or []
        if not nodes_conf:
            raise ValueError("Resource spec has no nodes")
        self.nodes: List[Node] = [Node(e) for e in nodes_conf]
        self.ssh_config_map = SSHConfigMap(info.get("ssh"))
        self.mesh_config: Dict[str, int] = dict(info.get("mesh", {}) or {})

        self._validate_and_set_chief()

    @staticmethod
    def _local_default_info() -> dict:
        import jax
        # Whatever the local platform (real TPU, axon tunnel, or CPU sim), the visible
        # devices are this spec's accelerators, declared under the `tpus:` key.
        n = len(jax.devices())
        return {"nodes": [{"address": "localhost", "tpus": n, "chief": True}]}

    def _validate_and_set_chief(self):
        addresses = [n.address for n in self.nodes]
        if len(set(addresses)) != len(addresses):
            raise ValueError("Duplicate node addresses in resource spec")
        chiefs = [n for n in self.nodes if n.chief]
        if len(self.nodes) == 1 and not chiefs:
            self.nodes[0].chief = True
            chiefs = [self.nodes[0]]
        if len(chiefs) != 1:
            raise ValueError(
                f"Exactly one chief required, found {len(chiefs)} "
                f"(reference requires the same: one chief node)")
        self._chief = chiefs[0]
        for n in self.nodes:
            if n.ssh_config_name is not None and n.ssh_config_name not in self.ssh_config_map:
                raise ValueError(
                    f"Node {n.address} references unknown ssh_config "
                    f"{n.ssh_config_name!r}; defined groups: {sorted(self.ssh_config_map)}")

    # --- accessors (reference resource_spec.py:80-158 property surface) ---

    @property
    def chief_address(self) -> str:
        return self._chief.address

    @property
    def node_addresses(self) -> List[str]:
        return [n.address for n in self.nodes]

    # Sorted iteration is load-bearing for deterministic port/process-index assignment —
    # every host must derive the same ordering independently (reference cluster.py:70-82).
    @property
    def sorted_nodes(self) -> List[Node]:
        return sorted(self.nodes, key=lambda n: (not n.chief, n.address))

    @property
    def accelerator_devices(self) -> List[Tuple[str, DeviceSpec]]:
        out = []
        for node in self.sorted_nodes:
            for dev in node.accelerator_devices:
                out.append((dev.name_string, dev))
        return out

    @property
    def tpu_devices(self) -> List[Tuple[str, DeviceSpec]]:
        return [(s, d) for s, d in self.accelerator_devices if d.device_type is DeviceType.TPU]

    @property
    def gpu_devices(self) -> List[Tuple[str, DeviceSpec]]:
        return [(s, d) for s, d in self.accelerator_devices if d.device_type is DeviceType.GPU]

    @property
    def cpu_devices(self) -> List[Tuple[str, DeviceSpec]]:
        out = []
        for node in self.sorted_nodes:
            for dev in node.cpu_devices:
                out.append((dev.name_string, dev))
        return out

    @property
    def num_accelerators(self) -> int:
        return len(self.accelerator_devices)

    @property
    def num_nodes(self) -> int:
        return len(self.nodes)

    def node_bandwidth(self, address: str) -> int:
        for n in self.nodes:
            if n.address == address:
                return n.network_bandwidth
        raise KeyError(address)

    def ssh_config_for(self, address: str) -> Optional[SSHConfig]:
        for n in self.nodes:
            if n.address == address:
                if n.ssh_config_name is None:
                    return None
                return self.ssh_config_map[n.ssh_config_name]
        raise KeyError(address)

    # Replica devices: the devices that carry data-parallel replicas. Reference strategy
    # builders use "all GPUs, plus the CPU of GPU-less nodes" (ps_strategy.py:37-56).
    @property
    def replica_devices(self) -> List[DeviceSpec]:
        out: List[DeviceSpec] = []
        for node in self.sorted_nodes:
            accs = node.accelerator_devices
            if accs:
                out.extend(accs)
            else:
                out.append(node.cpu_devices[0])
        return out

    def __repr__(self):
        return (f"ResourceSpec(nodes={self.node_addresses}, chief={self.chief_address}, "
                f"accelerators={self.num_accelerators})")
