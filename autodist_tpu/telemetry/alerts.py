"""Declarative SLO / drift alerting over the metric history.

Every detector the stack grew so far is hard-coded (the watchdog's 3-tick
stall rule, the health monitor's z-score) — operable fleets express "page me
when" as DATA. A rule here is a metric selector plus a predicate, evaluated
on every :class:`~autodist_tpu.telemetry.history.MetricsHistory` sample:

- ``threshold`` — compare a metric's current value against ``value`` with
  ``op`` (``> >= < <=``); ``for_s`` makes the condition hold continuously
  over that much history before firing (one bad tick is noise, five minutes
  of bad ticks is an incident).
- ``burn_rate`` — the multi-window SLO form: the ``q``-quantile of a LATENCY
  HISTOGRAM's delta over a long and a short window must BOTH exceed
  ``objective_s`` (the Google-SRE burn-rate construction: the long window
  proves budget is burning, the short window proves it is burning NOW — a
  recovered blip auto-resolves). Quantiles come from the shared
  :func:`telemetry.metrics.quantile` helper, windows from the history ring.
- ``drift`` — compare a live gauge against a REFERENCE band: ``ref`` explicit,
  ``ref_from="plan"`` derives it from the applied tuned plan's predicted
  breakdown (:func:`telemetry.profiling.applied_plan` — the Automap-style
  "live shares left the plan's predicted bound" trigger ROADMAP 4's online
  retuner consumes), ``ref_from="window_max"`` self-references the metric's
  own windowed peak (MFU collapse). ``direction`` picks the bad side;
  ``relative=True`` scales ``band`` by the reference.

Metric selectors ending in ``.*`` fan out over every matching registry name
and take the WORST value for the rule's direction (``ps.worker.last_seen_s.*``
alerts on the most-silent worker).

Firing books ``alert.active.<rule>``/``alert.active`` gauges (they ride the
``/metrics`` exposition and the ``status`` opcode with zero extra wiring),
emits a structured ``alert`` event into the existing ring, bumps
``alert.fired``, triggers the flight recorder THROUGH ITS DEBOUNCE, and
honors ``AUTODIST_ALERT_ACTION``: ``warn`` logs (rate-limited), ``record``
arms a recorder on demand, ``halt`` raises :class:`AlertHalt` out of the
sampling loop (the train loop propagates it; background samplers catch and
log), ``recover`` raises :class:`AlertRecover` — the train loop rolls back
to its last-known-good snapshot and resumes (``parallel/recovery.py``).
Rules load from ``AUTODIST_ALERT_RULES`` (a JSON file path or inline
JSON) on top of :data:`DEFAULT_RULES`; a malformed rule WARNS AND IS
SKIPPED — alerting must never crash the loop it watches.
"""

import dataclasses
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["AlertRule", "AlertEngine", "AlertHalt", "AlertRecover",
           "DEFAULT_RULES", "load_rules", "set_engine", "get_engine",
           "get_or_create", "active_alerts", "alerts_snapshot"]

ACTIONS = ("warn", "record", "halt", "recover")
KINDS = ("threshold", "burn_rate", "drift")
_OPS = {">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b}

# Shipped defaults — the incidents the existing planes can already diagnose
# but nothing watches for. AUTODIST_ALERT_RULES entries with the same name
# override; ``{"defaults": false}`` in the loaded document drops them.
DEFAULT_RULES: List[Dict[str, Any]] = [
    # Serving SLO: p99 total latency burning through a 1s objective in both
    # the 5-minute and 1-minute windows. The objective must sit STRICTLY
    # below MS_BUCKETS' top finite edge (2.5s): the shared quantile
    # estimator answers at most that edge (the +inf bucket's honest lower
    # bound), so an objective at/above it could never be exceeded and the
    # rule could never fire.
    {"name": "serve_p99_burn", "kind": "burn_rate",
     "metric": "serve.latency_s.total", "q": 0.99, "objective_s": 1.0,
     "long_s": 300.0, "short_s": 60.0},
    # Input-pipeline drift: the data_wait share left the tuned plan's
    # predicted bound (the plan predicts ~0 data_wait; a loader regression
    # shows up here first — ROADMAP 5's gate signal).
    {"name": "data_wait_drift", "kind": "drift",
     "metric": "train.attr.data_wait", "ref_from": "plan", "band": 0.25,
     "direction": "above", "for_s": 0.0},
    # Staleness: a worker silent for two minutes is parked at the bound or
    # gone (the watchdog flags it; this makes it a declarative page).
    {"name": "worker_stalled", "kind": "threshold",
     "metric": "ps.worker.last_seen_s.*", "op": ">", "value": 120.0},
    # MFU collapse: achieved MFU dropped below half its own 10-minute peak
    # (a straggler, a thermal throttle, a bad plan hot-swap).
    {"name": "mfu_collapse", "kind": "drift", "metric": "train.mfu",
     "ref_from": "window_max", "window_s": 600.0, "band": 0.5,
     "relative": True, "direction": "below"},
    # Memory pressure: the memory plane's worst-device used/limit ratio
    # (mem.pressure, booked by every sample_device_memory pass) held above
    # the default AUTODIST_MEM_PRESSURE threshold for 30s. Sustained, not
    # a spike: one fragmentation burp at a compile boundary should not
    # page. On serving kinds the same plane also tightens paged-KV
    # admission (memplane.kv_admission_holdback) — the rule is the page,
    # the holdback is the reflex.
    {"name": "mem_pressure", "kind": "threshold", "metric": "mem.pressure",
     "op": ">", "value": 0.92, "for_s": 30.0},
]


class AlertHalt(RuntimeError):
    """Raised out of the sampling call under ``AUTODIST_ALERT_ACTION=halt``:
    an alert rule fired and policy says stop. Carries the firing records,
    and — when the train loop is the sampler — the live ``TrainState`` on
    ``.state`` (attached at the raise's boundary call site, the
    :class:`HealthHalt` contract: a halt must leave the state inspectable
    and checkpointable, not discard the run's progress)."""

    def __init__(self, fired: List[Dict[str, Any]]):
        names = ",".join(sorted({f["rule"] for f in fired}))
        super().__init__(f"alert rule(s) fired with action=halt: {names}")
        self.fired = fired
        self.state = None   # the live TrainState, when a train loop raised


class AlertRecover(AlertHalt):
    """The ``AUTODIST_ALERT_ACTION=recover`` control signal — the health
    plane's recover action, driven by a declarative rule instead of the
    numerics bundle. ``train()`` catches it, rolls back to the newest
    last-known-good snapshot (``parallel/recovery.py``) and resumes,
    escalating after ``AUTODIST_RECOVER_MAX`` attempts; background samplers
    (timer/scheduler threads) catch it as the :class:`AlertHalt` it
    subclasses and log — a loop with live requests is not theirs to roll
    back."""


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule (see the module docstring for the grammar)."""

    name: str
    kind: str                       # threshold | burn_rate | drift
    metric: str                     # registry name; trailing ".*" fans out
    op: str = ">"                   # threshold comparator
    value: float = 0.0              # threshold bound
    for_s: float = 0.0              # condition must hold this long
    q: float = 0.99                 # burn-rate quantile
    objective_s: float = 1.0        # burn-rate SLO target for the quantile
    long_s: float = 300.0           # burn-rate long window
    short_s: float = 60.0           # burn-rate short window
    band: float = 0.1               # drift band width
    direction: str = "above"        # drift bad side: above | below
    ref: Optional[float] = None     # drift explicit reference
    ref_from: str = ""              # drift reference source: plan | window_max
    relative: bool = False          # drift band scales by the reference
    window_s: float = 600.0         # drift window_max lookback
    min_coverage: float = 0.5       # burn-rate: each window's sample span
    #                                 must cover this fraction of it

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"rule {self.name!r}: unknown kind "
                             f"{self.kind!r}; valid: {', '.join(KINDS)}")
        if not self.name or not self.metric:
            raise ValueError("a rule needs a non-empty name and metric")
        if self.kind == "threshold" and self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {self.op!r}; "
                             f"valid: {', '.join(_OPS)}")
        if self.direction not in ("above", "below"):
            raise ValueError(f"rule {self.name!r}: direction must be "
                             f"'above' or 'below'")
        if self.kind == "drift" and self.ref is None \
                and self.ref_from not in ("plan", "window_max"):
            raise ValueError(f"rule {self.name!r}: drift needs ref, or "
                             f"ref_from 'plan' or 'window_max'")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AlertRule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"rule {d.get('name', '?')!r}: unknown "
                             f"field(s) {', '.join(unknown)}")
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    # ----------------------------------------------------------- evaluation

    def _select(self, metrics: Dict[str, Any]) -> Optional[float]:
        """The rule's scalar from one sample's metrics: exact name, or the
        worst match of a ``prefix.*`` fan-out (max for 'above'-is-bad rules
        and thresholds that fire upward, min for the opposite side)."""
        if not self.metric.endswith(".*"):
            v = metrics.get(self.metric)
            return float(v) if isinstance(v, (int, float)) else None
        prefix = self.metric[:-1]   # keep the trailing dot
        vals = [float(v) for k, v in metrics.items()
                if k.startswith(prefix) and isinstance(v, (int, float))]
        if not vals:
            return None
        bad_high = (self.op in (">", ">=") if self.kind == "threshold"
                    else self.direction == "above")
        return max(vals) if bad_high else min(vals)

    def _holds(self, value: float, bound: float) -> bool:
        if self.kind == "threshold":
            return _OPS[self.op](value, bound)
        if self.direction == "above":
            return value - bound > self._band(bound)
        return bound - value > self._band(bound)

    def _band(self, ref: float) -> float:
        return abs(ref) * self.band if self.relative else self.band

    def _reference(self, history) -> Optional[float]:
        if self.ref is not None:
            return float(self.ref)
        if self.ref_from == "plan":
            from autodist_tpu.telemetry import profiling as _profiling
            plan = _profiling.applied_plan()
            pred = (plan or {}).get("predicted") or {}
            step_s = pred.get("step_s")
            breakdown = pred.get("breakdown") or {}
            if not step_s:
                return None
            # The plan's predicted per-step breakdown as attribution shares:
            # phases the model does not price (readback) are predicted 0 —
            # exactly the bound drift is measured against. data_wait maps
            # to the cost model's residual-loader term (max(0, loader_s -
            # hidden_s)): a plan that priced a slow loader as hidden
            # behind prefetch_depth predicts ~0 and the drift rule pages
            # the moment the pipeline stops hiding it.
            phase = self.metric.rsplit(".", 1)[-1]
            share = {"compute": breakdown.get("compute_s", 0.0),
                     "comm": breakdown.get("comm_s", 0.0),
                     "host": breakdown.get("host_s", 0.0),
                     "data_wait": breakdown.get("data_wait_s", 0.0)
                     }.get(phase, 0.0)
            return float(share) / float(step_s) if share else 0.0
        if self.ref_from == "window_max":
            series = [v for _, v in history.series(self.metric,
                                                   window_s=self.window_s)
                      if isinstance(v, (int, float))]
            return max(series) if series else None
        return None

    def evaluate(self, history) -> Optional[Dict[str, Any]]:
        """Firing detail dict when the rule's condition holds on ``history``'s
        latest sample (and over ``for_s`` of it), else None."""
        latest = history.latest()
        if latest is None:
            return None
        if self.kind == "burn_rate":
            return self._evaluate_burn(history)
        if self.kind == "drift":
            bound = self._reference(history)
            if bound is None:
                return None       # no reference yet -> the rule is inert
        else:
            bound = self.value
        value = self._select(latest["metrics"])
        if value is None or not math.isfinite(value):
            return None
        if not self._holds(value, bound):
            return None
        if self.for_s > 0:
            # Duration: the condition must hold over for_s of ACTUAL history
            # — which needs (a) at least one sample OLD enough to prove the
            # ring covers the window (a single fresh sample proves nothing
            # about duration), and (b) every sample inside the window
            # agreeing. The boundary sample itself must agree too: it is the
            # evidence the condition already held when the window opened.
            cut = latest["t_mono_s"] - self.for_s
            older = [s for s in history.samples() if s["t_mono_s"] <= cut]
            if not older:
                return None
            for s in history.window(self.for_s) + [older[-1]]:
                v = self._select(s["metrics"])
                if v is None or not self._holds(v, bound):
                    return None
        detail = {"value": round(value, 6), "bound": round(float(bound), 6)}
        if self.kind == "drift":
            detail["band"] = round(self._band(bound), 6)
        return detail

    def _evaluate_burn(self, history) -> Optional[Dict[str, Any]]:
        qs = {}
        for label, win_s in (("long", self.long_s), ("short", self.short_s)):
            window = history.window(win_s)
            if len(window) < 2:
                return None       # a burn rate needs a window to burn over
            # Coverage: the window's samples must SPAN a meaningful fraction
            # of it — a process 20s old would otherwise evaluate its "5m"
            # window over the same two fresh samples as the 1m one, and a
            # warmup blip would page as a sustained burn (the threshold
            # predicate's for_s coverage rule, applied per window).
            span = window[-1]["t_mono_s"] - window[0]["t_mono_s"]
            if span < self.min_coverage * win_s:
                return None
            new = window[-1]["metrics"].get(self.metric)
            old = window[0]["metrics"].get(self.metric)
            if not isinstance(new, dict) or not isinstance(old, dict):
                return None
            delta = {k: new.get(k, 0) - old.get(k, 0) for k in new
                     if isinstance(new.get(k), (int, float))}
            q = _metrics.quantile(delta, self.q)
            if q is None or q <= self.objective_s:
                return None
            # :g, not int(): int truncates (q=0.57 -> "p56") and collapses
            # sub-percent quantiles (0.999 and 0.995 both -> "p99").
            qs[f"p{self.q * 100:g}_{label}_s"] = round(q, 6)
        return dict(qs, objective_s=self.objective_s)


def load_rules(raw: Optional[str] = None) -> List[AlertRule]:
    """The rule set: :data:`DEFAULT_RULES` overlaid with
    ``AUTODIST_ALERT_RULES`` (or ``raw``) — a JSON file path, or inline JSON
    (``[...]`` rule list, or ``{"rules": [...], "defaults": false}`` to drop
    the shipped set). Same-name entries REPLACE defaults. Every malformed
    rule (and an unreadable/unparseable source) degrades to a warning —
    a typo in an alert file must never take down the run it watches."""
    if raw is None:
        raw = str(const.ENV.AUTODIST_ALERT_RULES.val)
    loaded: List[Dict[str, Any]] = []
    keep_defaults = True
    if raw:
        try:
            text = raw
            if not raw.lstrip().startswith(("[", "{")):
                with open(raw) as f:
                    text = f.read()
            doc = json.loads(text)
            if isinstance(doc, dict):
                keep_defaults = bool(doc.get("defaults", True))
                doc = doc.get("rules", [])
            if not isinstance(doc, list):
                raise ValueError("alert rules document must be a list or "
                                 "{'rules': [...]}")
            loaded = doc
        except (OSError, ValueError, TypeError) as e:
            logging.warning("alerts: cannot load AUTODIST_ALERT_RULES=%r "
                            "(%s); keeping the shipped defaults", raw, e)
            loaded, keep_defaults = [], True
    by_name: Dict[str, AlertRule] = {}
    source = (DEFAULT_RULES if keep_defaults else []) + loaded
    for d in source:
        try:
            rule = AlertRule.from_dict(dict(d))
        except (TypeError, ValueError) as e:
            logging.warning("alerts: skipping malformed rule %r: %s", d, e)
            continue
        by_name[rule.name] = rule   # later (loaded) entries replace defaults
    return list(by_name.values())


class _RuleState:
    __slots__ = ("active", "since_mono", "since_wall", "detail")

    def __init__(self):
        self.active = False
        self.since_mono = 0.0
        self.since_wall = 0.0
        self.detail: Dict[str, Any] = {}


class AlertEngine:
    """Evaluates a rule set on every history sample and owns the reaction.

    One engine per process (the history's default); tests construct their
    own. Thread-safe for the same reason the history is: boundary, scheduler
    and timer threads may all sample."""

    WARN_EVERY_S = 60.0
    RESOLVED_KEEP = 32

    def __init__(self, rules: Optional[Sequence[AlertRule]] = None,
                 action: Optional[str] = None, recorder=None):
        self.rules = list(rules) if rules is not None else load_rules()
        self.action = (action if action is not None
                       else str(const.ENV.AUTODIST_ALERT_ACTION.val))
        if self.action not in ACTIONS:
            raise ValueError(f"unknown alert action {self.action!r}; "
                             f"valid: {', '.join(ACTIONS)}")
        self._recorder = recorder   # None -> resolved per policy at fire time
        self._lock = san_lock()
        self._state: Dict[str, _RuleState] = {r.name: _RuleState()
                                              for r in self.rules}
        self._resolved: List[Dict[str, Any]] = []
        self._last_warn = -math.inf
        self._warned_rules: set = set()
        reg = _metrics.registry()
        self._active_gauge = reg.gauge("alert.active")
        self._fired_counter = reg.counter("alert.fired")

    # ------------------------------------------------------------ evaluation

    def evaluate(self, history) -> List[Dict[str, Any]]:
        """One tick: run every rule against ``history``, book the transition
        effects, return the NEWLY-fired records. Raises :class:`AlertHalt`
        (after booking everything) when a new firing meets ``action=halt``."""
        now, wall = time.monotonic(), time.time()
        fired: List[Dict[str, Any]] = []
        resolved: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                detail = rule.evaluate(history)
            except Exception as e:   # a sick rule warns once, never crashes
                if rule.name not in self._warned_rules:
                    self._warned_rules.add(rule.name)
                    logging.warning("alerts: rule %r failed to evaluate "
                                    "(%s); treating as not firing",
                                    rule.name, e)
                detail = None
            if detail is not None and rule.kind == "burn_rate":
                # A burn-rate firing names a concrete traceable request: the
                # latency histogram's slowest-in-window exemplar (rid + phase
                # breakdown) rides into the alert record — and from there
                # into status snapshots and the flight-recorder manifest.
                inst = _metrics.registry().get(rule.metric)
                ex = (inst.exemplar()
                      if isinstance(inst, _metrics.Histogram) else None)
                if ex is not None:
                    detail = dict(detail, exemplar=ex)
            with self._lock:
                st = self._state.setdefault(rule.name, _RuleState())
                if detail is not None and not st.active:
                    st.active, st.detail = True, detail
                    st.since_mono, st.since_wall = now, wall
                    fired.append({"rule": rule.name, "kind": rule.kind,
                                  "metric": rule.metric, **detail})
                elif detail is not None:
                    st.detail = detail   # refresh the live numbers
                elif st.active:
                    st.active = False
                    resolved.append({
                        "rule": rule.name, "kind": rule.kind,
                        "metric": rule.metric, **st.detail,
                        "fired_t_wall_s": round(st.since_wall, 3),
                        "duration_s": round(now - st.since_mono, 3)})
            _metrics.gauge(f"alert.active.{rule.name}").set(
                1 if detail is not None else 0)
        with self._lock:
            self._active_gauge.set(sum(1 for s in self._state.values()
                                       if s.active))
            for rec in resolved:
                self._resolved.append(rec)
            del self._resolved[:max(0, len(self._resolved)
                                    - self.RESOLVED_KEEP)]
        for rec in resolved:
            _metrics.event("alert", state="resolved", **rec)
            logging.info("alerts: %s resolved after %.1fs", rec["rule"],
                         rec["duration_s"])
        if fired:
            self._react(fired)
        return fired

    def _react(self, fired: List[Dict[str, Any]]):
        from autodist_tpu.telemetry import recorder as _recorder
        for rec in fired:
            self._fired_counter.inc()
            _metrics.event("alert", state="firing", **rec)
        names = ",".join(sorted({f["rule"] for f in fired}))
        if self.action == "record":
            # record EXPLICITLY asks for snapshots: arm on demand (the
            # health monitor's exact contract).
            if self._recorder is None:
                self._recorder = _recorder.get_or_create()
            path = self._recorder.maybe_record(f"alert.{names}")
        elif self._recorder is not None:
            path = self._recorder.maybe_record(f"alert.{names}")
        else:
            # warn/halt snapshot only through an ARMED recorder
            # (AUTODIST_RECORDER=1 or set_recorder) — the alert event is the
            # trigger, the action decides how loudly to react.
            path = _recorder.maybe_record(f"alert.{names}")
        if path:
            logging.warning("alerts: %s firing — flight-recorder snapshot "
                            "at %s", names, path)
        else:
            now = time.monotonic()
            if now - self._last_warn >= self.WARN_EVERY_S:
                self._last_warn = now
                logging.warning("alerts: %s firing: %s", names, fired[-1])
        if self.action == "halt":
            raise AlertHalt(fired)
        if self.action == "recover":
            raise AlertRecover(fired)

    # --------------------------------------------------------------- queries

    def active(self) -> List[Dict[str, Any]]:
        """Wire-encodable records of the currently-firing rules."""
        now, out = time.monotonic(), []
        with self._lock:
            for rule in self.rules:
                st = self._state.get(rule.name)
                if st is not None and st.active:
                    out.append({"rule": rule.name, "kind": rule.kind,
                                "metric": rule.metric, **st.detail,
                                "for_s": round(now - st.since_mono, 3),
                                "fired_t_wall_s": round(st.since_wall, 3)})
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The ``status`` opcode's ``alerts`` section: active firings plus
        the recently-resolved ring (newest last)."""
        with self._lock:
            resolved = list(self._resolved)
        return {"active": self.active(), "resolved": resolved,
                "rules": len(self.rules), "action": self.action}


# ------------------------------------------------------------ process global

_ENGINE: Optional[AlertEngine] = None
_ENGINE_LOCK = san_lock()


def set_engine(engine: Optional[AlertEngine]):
    global _ENGINE
    with _ENGINE_LOCK:
        _ENGINE = engine


def get_engine() -> Optional[AlertEngine]:
    return _ENGINE


def get_or_create() -> AlertEngine:
    """The process engine, created from the env rule set on first use (the
    default engine every :class:`MetricsHistory` evaluates through). A
    typo'd ``AUTODIST_ALERT_ACTION`` degrades to ``warn`` with a warning —
    this is called lazily from sampling hooks inside the train loop and the
    serving scheduler thread, where a raise would take down the loop the
    alerting is supposed to watch."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            try:
                _ENGINE = AlertEngine()
            except ValueError as e:
                logging.warning("alerts: %s; degrading to action='warn'", e)
                _ENGINE = AlertEngine(action="warn")
        return _ENGINE


def active_alerts() -> List[Dict[str, Any]]:
    """Currently-firing alert records, or [] when no engine is installed —
    the NON-CREATING accessor diagnostics use (the flight-recorder manifest
    must not grow an alert engine as a side effect of snapshotting)."""
    eng = _ENGINE
    return eng.active() if eng is not None else []


def alerts_snapshot() -> Dict[str, Any]:
    """The ``status``-opcode section: the engine's snapshot, or an empty
    shell when alerting never armed (pollers see a stable schema)."""
    eng = _ENGINE
    if eng is None:
        return {"active": [], "resolved": [], "rules": 0, "action": ""}
    return eng.snapshot()
