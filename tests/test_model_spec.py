"""ModelSpec IR — parity with reference tests/test_graph_item.py (capture tables)."""

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.model_spec import ModelSpec, detect_sparse_params


def _params():
    return {
        "dense": {"w": jnp.zeros((4, 3)), "b": jnp.zeros((3,))},
        "emb": {"table": jnp.zeros((100, 8))},
    }


def test_names_shapes_dtypes():
    spec = ModelSpec(_params())
    assert set(spec.params) == {"dense/w", "dense/b", "emb/table"}
    assert spec["dense/w"].shape == (4, 3)
    assert spec["emb/table"].byte_size == 100 * 8 * 4
    assert spec["dense/b"].size == 3


def test_unflatten_roundtrip():
    params = _params()
    spec = ModelSpec(params)
    leaves = spec.flatten(params)
    tree = spec.unflatten(leaves)
    assert jax.tree_util.tree_structure(tree) == jax.tree_util.tree_structure(params)


def test_from_init_fn_uses_eval_shape():
    calls = []

    def init():
        calls.append(1)
        return {"w": jnp.zeros((2, 2))}

    spec = ModelSpec.from_init_fn(init)
    assert spec["w"].shape == (2, 2)


def test_trainable_filter():
    spec = ModelSpec(_params(), trainable_filter=lambda n: not n.startswith("emb"))
    assert "emb/table" not in spec.trainable
    assert "dense/w" in spec.trainable


def test_sparse_detection_embedding_lookup():
    """A param consumed only via take/gather is row-sparse (reference IndexedSlices)."""
    params = _params()

    def loss(p, idx, x):
        e = jnp.take(p["emb"]["table"], idx, axis=0)       # embedding lookup
        h = x @ p["dense"]["w"] + p["dense"]["b"]
        return jnp.sum(e) + jnp.sum(h)

    idx = np.array([1, 2, 3])
    x = np.ones((2, 4), np.float32)
    sparse = detect_sparse_params(loss, params, idx, x)
    assert sparse == ["emb/table"]

    spec = ModelSpec.from_loss_fn(loss, params, idx, x)
    assert spec["emb/table"].sparse
    assert not spec["dense/w"].sparse


def test_dense_use_disables_sparse_detection():
    params = {"table": jnp.zeros((10, 4))}

    def loss(p, idx):
        # gather AND a dense use -> dense gradient
        return jnp.sum(jnp.take(p["table"], idx, axis=0)) + jnp.sum(p["table"])

    assert detect_sparse_params(loss, params, np.array([0, 1])) == []
