"""Linear regression — the canonical minimum slice.

Port of reference ``examples/linear_regression.py:15-71``: a single-device model
wrapped in ``AutoDist(...).scope()``, trained distributed for a few steps with the
loss decreasing. Runs on whatever JAX platform is active (real TPU chip, or the
8-device CPU-sim mesh under JAX_PLATFORMS=cpu).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))  # run from checkout

import numpy as np

import jax.numpy as jnp

from autodist_tpu import AutoDist
from autodist_tpu.strategy import AllReduce

import optax

TRUE_W, TRUE_B = 3.0, 2.0
NUM_EXAMPLES = 1024


def make_data(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(NUM_EXAMPLES).astype(np.float32)
    noise = rng.randn(NUM_EXAMPLES).astype(np.float32)
    y = x * TRUE_W + TRUE_B + noise
    return x, y


def main():
    x, y = make_data()
    ad = AutoDist(strategy_builder=AllReduce())  # local spec from visible devices

    with ad.scope():
        params = {"w": jnp.zeros(()), "b": jnp.zeros(())}

        def loss_fn(p, batch):
            pred = batch["x"] * p["w"] + p["b"]
            return jnp.mean((batch["y"] - pred) ** 2)

    step = ad.function(loss_fn, params, optax.sgd(0.05),
                       example_batch={"x": x[:8], "y": y[:8]})

    losses = []
    for epoch in range(10):
        loss = step({"x": x, "y": y})
        losses.append(float(loss))
        print(f"step {epoch}: loss={losses[-1]:.4f}")

    final = step.get_state().params
    print(f"w={float(final['w']):.3f} (true {TRUE_W}), b={float(final['b']):.3f} (true {TRUE_B})")
    assert losses[-1] < losses[0], "loss must decrease"
    return losses


if __name__ == "__main__":
    main()
