"""MovieLens preprocessing: the reference's recommendation pipeline contract
(filter >= 20 ratings, zero-index, leave-last-out, eval negatives excluding
seen items, HR@K/NDCG@K) — offline, numpy, shard-writable."""

import numpy as np
import pytest

from autodist_tpu.data import movielens
from shardmap_compat import requires_shard_map


def _write_ratings(path, rows, sep=",", header=True):
    with open(path, "w") as f:
        if header:
            f.write(sep.join(["user_id", "item_id", "rating", "timestamp"])
                    + "\n")
        for r in rows:
            f.write(sep.join(str(x) for x in r) + "\n")


def _rows(n_users=4, n_per_user=25, n_items=50, seed=0):
    """Synthetic interactions with DISTINCT items per user, increasing
    timestamps, and non-contiguous raw ids (to exercise zero-indexing)."""
    rng = np.random.RandomState(seed)
    rows = []
    for u in range(n_users):
        items = rng.choice(n_items, size=n_per_user, replace=False)
        for t, i in enumerate(items):
            rows.append((100 + 7 * u, 1000 + 3 * int(i), 5, 10_000 + t))
    return rows


def test_load_filter_zero_index_and_leave_last_out(tmp_path):
    rows = _rows(n_users=4, n_per_user=25)
    # One user below the threshold: must be dropped entirely.
    rows += [(999, 1000, 5, 1), (999, 1003, 4, 2)]
    path = str(tmp_path / "ratings.csv")
    _write_ratings(path, rows)
    data = movielens.load_ratings(path, min_ratings=20)

    assert data.num_users == 4                      # 999 filtered out
    assert data.train_users.max() == 3              # zero-indexed
    assert data.train_items.max() < data.num_items
    assert len(data.eval_users) == 4                # one eval row per user
    assert data.num_train == 4 * 24                 # last item held out
    # The eval item is each user's LAST-timestamped interaction.
    raw_by_user = {}
    for u, i, _, t in rows[:-2]:
        if u not in raw_by_user or t > raw_by_user[u][1]:
            raw_by_user[u] = (i, t)
    # Rebuild the raw->zero-index item map the loader used.
    kept_items = sorted({i for u, i, _, t in rows[:-2]})
    item_map = {raw: idx for idx, raw in enumerate(kept_items)}
    expected = {uu: item_map[i] for uu, (i, _) in raw_by_user.items()}
    for u_new, i_new in zip(data.eval_users, data.eval_items):
        u_raw = sorted(raw_by_user)[u_new]          # users zero-indexed sorted
        assert expected[u_raw] == i_new


def test_ml1m_double_colon_format(tmp_path):
    path = str(tmp_path / "ratings.dat")
    _write_ratings(path, _rows(n_users=2), sep="::", header=False)
    data = movielens.load_ratings(path, min_ratings=20)
    assert data.num_users == 2 and data.num_train == 2 * 24


def test_training_epoch_negatives_and_labels():
    rows = _rows(n_users=3)
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "r.csv")
        _write_ratings(path, rows)
        data = movielens.load_ratings(path, min_ratings=20)
    epoch = movielens.sample_training_epoch(data, num_neg=4, seed=1)
    n = data.num_train
    assert len(epoch["users"]) == n * 5
    assert epoch["labels"].sum() == n               # 1 positive : 4 negatives
    assert epoch["items"].min() >= 0
    assert epoch["items"].max() < data.num_items
    # Per-user example count is preserved (positives + 4x negatives each).
    for u in range(data.num_users):
        want = 5 * (data.train_users == u).sum()
        assert (epoch["users"] == u).sum() == want
    # A different seed re-samples the negatives (per-epoch regeneration).
    epoch2 = movielens.sample_training_epoch(data, num_neg=4, seed=2)
    assert not np.array_equal(epoch["items"], epoch2["items"])


def test_eval_negatives_exclude_seen_items(tmp_path):
    path = str(tmp_path / "r.csv")
    _write_ratings(path, _rows(n_users=3, n_per_user=25, n_items=200))
    data = movielens.load_ratings(path, min_ratings=20)
    # num_items counts KEPT (interacted) items only — draw within that pool.
    negs = movielens.sample_eval_negatives(data, num_negatives=30, seed=0)
    assert negs.shape == (3, 30)
    for row, u in enumerate(data.eval_users):
        seen = set(data.train_items[data.train_users == u].tolist())
        seen.add(int(data.eval_items[row]))
        assert not seen & set(negs[row].tolist())   # never a seen item
        assert len(set(negs[row].tolist())) == 30   # distinct


def test_hit_rate_and_ndcg_oracle():
    """A scorer that ranks the true item first gives HR=NDCG=1; one that
    ranks it below k gives 0; a rank-2 scorer gives NDCG=1/log2(3)."""
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "r.csv")
        _write_ratings(path, _rows(n_users=3, n_items=300))
        data = movielens.load_ratings(path, min_ratings=20)

    truth = {int(u): int(i) for u, i in zip(data.eval_users, data.eval_items)}

    def oracle(users, items):
        return np.array([1.0 if truth[int(u)] == int(i) else 0.0
                         for u, i in zip(users, items)])

    hr, ndcg = movielens.hit_rate_and_ndcg(oracle, data, k=10, seed=3,
                                           num_negatives=30)
    assert hr == 1.0 and ndcg == 1.0

    def anti_oracle(users, items):
        return -oracle(users, items)

    hr, ndcg = movielens.hit_rate_and_ndcg(anti_oracle, data, k=10, seed=3,
                                           num_negatives=30)
    assert hr == 0.0 and ndcg == 0.0

    def one_better(users, items):
        # Exactly one negative outranks the positive -> rank 1 for every user.
        base = oracle(users, items)
        out = base.copy()
        boosted = set()
        for j, (u, i) in enumerate(zip(users, items)):
            if base[j] == 0.0 and int(u) not in boosted:
                out[j] = 2.0
                boosted.add(int(u))
        return out

    hr, ndcg = movielens.hit_rate_and_ndcg(one_better, data, k=10, seed=3,
                                           num_negatives=30)
    assert hr == 1.0
    np.testing.assert_allclose(ndcg, 1.0 / np.log2(3))

    # A CONSTANT scorer (a model that learned nothing) must score at CHANCE
    # level: rank uniform over the full candidate list, so HR@10 = 10/31 and
    # NDCG@10 = mean over positions 0..30 of (p<10)/log2(p+2) — including
    # when the clamp leaves fewer than 2k negatives (the all-or-nothing
    # failure mode of point-estimate tie ranks).
    flat = lambda u, i: np.zeros(len(u))  # noqa: E731
    hr, ndcg = movielens.hit_rate_and_ndcg(flat, data, k=10, seed=3,
                                           num_negatives=30)
    np.testing.assert_allclose(hr, 10 / 31)
    np.testing.assert_allclose(
        ndcg, np.mean([1 / np.log2(p + 2) for p in range(10)] + [0] * 21))
    hr, ndcg = movielens.hit_rate_and_ndcg(flat, data, k=10, seed=3,
                                           num_negatives=18)
    np.testing.assert_allclose(hr, 10 / 19)  # NOT 1.0


@requires_shard_map
def test_ncf_example_trains_on_real_ratings(tmp_path):
    """End-to-end: the NCF benchmark trains on a ratings file and reports
    HR@10/NDCG@10 on the held-out items."""
    path = str(tmp_path / "ratings.csv")
    _write_ratings(path, _rows(n_users=6, n_per_user=24, n_items=40, seed=2))
    import examples.benchmark.ncf as bench
    avg = bench.main(["--steps", "4", "--batch_size", "64", "--log_every", "2",
                      "--ratings", path])
    assert avg is None or avg >= 0


def test_shard_writer_roundtrip(tmp_path):
    path = str(tmp_path / "r.csv")
    _write_ratings(path, _rows(n_users=3))
    data = movielens.load_ratings(path, min_ratings=20)
    files = movielens.write_training_shards(data, str(tmp_path / "shards"),
                                            num_neg=2, rows_per_shard=50)
    from autodist_tpu.data import DataLoader
    dl = DataLoader(files=files, batch_size=16, shuffle=False)
    b = dl.next()
    assert set(b) == {"users", "items", "labels"}
    assert dl.n_rows == data.num_train * 3
    dl.close()


def test_low_activity_dataset_raises(tmp_path):
    path = str(tmp_path / "r.csv")
    _write_ratings(path, [(1, 1, 5, 1), (1, 2, 5, 2)])
    with pytest.raises(ValueError, match="min_ratings"):
        movielens.load_ratings(path, min_ratings=20)