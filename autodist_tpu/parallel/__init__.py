"""Kernel backend: mesh bootstrap, sharding compiler, synchronizers, partitioners.

This package is the TPU-native counterpart of the reference's graph-rewriting kernel
backend (``autodist/kernel/*``): instead of mutating a ``tf.Graph``, it compiles a
Strategy into per-parameter ``PartitionSpec``s plus a gradient-synchronization transform
applied around the user's step function under ``jax.jit`` over a ``jax.sharding.Mesh``.
"""

from autodist_tpu.parallel.mesh import build_mesh, standard_mesh_shape, STANDARD_AXES

__all__ = ["build_mesh", "standard_mesh_shape", "STANDARD_AXES"]
