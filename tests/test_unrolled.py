"""Fused multi-step execution: ``run_many`` + ``train(unroll=K)``.

The fused path dispatches K optimizer steps as ONE compiled ``lax.scan`` over
the existing step body, so it must be a pure performance transform: bit-identical
final state to K sequential ``run()`` calls (same step body, same shardings —
asserted exactly, not approximately), the same fetch contract with a leading
``[K]`` stack axis, and ``train(..., unroll=K)`` preserving the per-step loop's
checkpoint/eval/resume semantics (cadence points force block boundaries).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, train
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.runner import BatchBlock
from autodist_tpu.strategy import AllReduce, PS

BATCH = 32


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)


def _params():
    rng = np.random.RandomState(7)
    return {"w": rng.randn(4, 1).astype(np.float32),
            "b": np.zeros((1,), np.float32)}


def _batch_fn(i):
    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(BATCH, 4).astype(np.float32),
            "y": rng.randn(BATCH, 1).astype(np.float32)}


def _session(accum=1, has_aux=False, loss=None):
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        loss if loss is not None else _loss, _params(), optax.adam(1e-2),
        example_batch=_batch_fn(0), accumulation_steps=accum, has_aux=has_aux)
    return runner, runner.init(_params())


def _assert_trees_equal(a, b):
    """Bitwise equality, leaf by leaf (the fused path is a dispatch transform,
    not a numeric one)."""
    a, b = jax.device_get(a), jax.device_get(b)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("accum", [1, 2])
def test_run_many_bit_exact_vs_sequential(accum):
    K = 6
    batches = [_batch_fn(i) for i in range(K)]

    runner_a, state_a = _session(accum=accum)
    seq_losses = []
    for b in batches:
        state_a, loss = runner_a.run(state_a, b)
        seq_losses.append(jax.device_get(loss))

    runner_b, state_b = _session(accum=accum)
    state_b, losses = runner_b.run_many(state_b, batches)

    assert losses.shape == (K,)
    np.testing.assert_array_equal(jax.device_get(losses), np.stack(seq_losses))
    _assert_trees_equal(state_b.params, state_a.params)
    _assert_trees_equal(state_b.opt_state, state_a.opt_state)
    assert int(state_b.step) == int(state_a.step) == K


def test_run_many_single_step_matches_run():
    runner_a, state_a = _session()
    state_a, loss_a = runner_a.run(state_a, _batch_fn(0))
    runner_b, state_b = _session()
    state_b, losses_b = runner_b.run_many(state_b, [_batch_fn(0)])
    np.testing.assert_array_equal(jax.device_get(losses_b),
                                  jax.device_get(loss_a)[None])
    _assert_trees_equal(state_b.params, state_a.params)


def test_run_many_repeated_blocks_with_donation():
    """Consecutive run_many calls donate the carried state (default) and still
    match 2K sequential steps exactly."""
    K = 3
    batches = [_batch_fn(i) for i in range(2 * K)]
    runner_a, state_a = _session()
    for b in batches:
        state_a, _ = runner_a.run(state_a, b)
    runner_b, state_b = _session()
    state_b, _ = runner_b.run_many(state_b, batches[:K])
    state_b, _ = runner_b.run_many(state_b, batches[K:])
    _assert_trees_equal(state_b.params, state_a.params)
    assert int(state_b.step) == 2 * K


def test_run_many_fetches_stack_per_step():
    """fetches=fn returns with a leading [K] axis; slice k equals the k-th
    sequential run's fetch (computed from that step's pre-update params)."""
    K = 3
    batches = [_batch_fn(i) for i in range(K)]
    preds = lambda p, b: b["x"] @ p["w"] + p["b"]  # noqa: E731

    runner_a, state_a = _session()
    seq = []
    for b in batches:
        state_a, (_, fetched) = runner_a.run(state_a, b, fetches=preds)
        seq.append(jax.device_get(fetched))

    runner_b, state_b = _session()
    state_b, (losses, stacked) = runner_b.run_many(state_b, batches,
                                                   fetches=preds)
    assert stacked.shape == (K, BATCH, 1)
    np.testing.assert_array_equal(jax.device_get(stacked), np.stack(seq))
    _assert_trees_equal(state_b.params, state_a.params)


def test_run_many_aux_stacks_and_matches():
    def loss_with_aux(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        per_ex = ((b["y"] - pred) ** 2)[:, 0]
        return jnp.mean(per_ex), {"mean_abs": jnp.mean(jnp.abs(per_ex)),
                                  "per_example": per_ex}

    K = 3
    batches = [_batch_fn(i) for i in range(K)]
    runner_a, state_a = _session(has_aux=True, loss=loss_with_aux)
    seq_aux = []
    for b in batches:
        state_a, (_, aux) = runner_a.run(state_a, b)
        seq_aux.append(jax.device_get(aux))

    runner_b, state_b = _session(has_aux=True, loss=loss_with_aux)
    state_b, (losses, auxes) = runner_b.run_many(state_b, batches)
    assert losses.shape == (K,)
    assert auxes["per_example"].shape == (K, BATCH)
    assert auxes["mean_abs"].shape == (K,)
    for k in range(K):
        np.testing.assert_array_equal(auxes["per_example"][k],
                                      seq_aux[k]["per_example"])
        np.testing.assert_array_equal(auxes["mean_abs"][k],
                                      seq_aux[k]["mean_abs"])
    _assert_trees_equal(state_b.params, state_a.params)


def test_run_many_accepts_prestacked_block():
    """A BatchBlock from shard_block (the device_prefetch unroll path) feeds
    run_many directly, skipping re-stacking."""
    K = 4
    batches = [_batch_fn(i) for i in range(K)]
    runner, state = _session()
    block = runner.shard_block(batches)
    assert isinstance(block, BatchBlock) and len(block) == K
    state, losses = runner.run_many(state, block)
    assert losses.shape == (K,)

    runner_a, state_a = _session()
    for b in batches:
        state_a, _ = runner_a.run(state_a, b)
    _assert_trees_equal(state.params, state_a.params)


def test_shard_block_device_resident_batches_stay_on_device():
    """Device-resident batch leaves stack on-device (no host readback) and
    produce the same block results as host batches."""
    K = 3
    host = [_batch_fn(i) for i in range(K)]
    runner, state = _session()
    resident = [jax.tree_util.tree_map(jnp.asarray, b) for b in host]
    block = runner.shard_block(resident)
    for leaf in jax.tree_util.tree_leaves(block.tree):
        assert leaf.shape[0] == K
    state, losses = runner.run_many(state, block)

    runner_h, state_h = _session()
    state_h, losses_h = runner_h.run_many(state_h, host)
    np.testing.assert_array_equal(jax.device_get(losses),
                                  jax.device_get(losses_h))
    _assert_trees_equal(state.params, state_h.params)


def test_device_prefetch_unroll_yields_blocks():
    from autodist_tpu.data.loader import DataLoader, device_prefetch
    rng = np.random.RandomState(5)
    loader = DataLoader({"x": rng.randn(96, 4).astype(np.float32),
                         "y": rng.randn(96, 1).astype(np.float32)},
                        batch_size=BATCH, native=False)
    try:
        runner, state = _session()
        it = device_prefetch(loader, runner, depth=2, unroll=2)
        block = next(it)
        assert isinstance(block, BatchBlock) and len(block) == 2
        state, losses = runner.run_many(state, block)
        assert losses.shape == (2,)
        it.close()   # stop the producer before its loader goes away
    finally:
        loader.close()


def test_shard_block_rejects_mismatched_structures():
    runner, _ = _session()
    good = _batch_fn(0)
    bad = {"x": good["x"]}  # missing "y"
    with pytest.raises(ValueError, match="structure"):
        runner.shard_block([good, bad])


def test_shard_block_rejects_ragged_shapes():
    """A smaller final batch (fine per-step via recompile) must fail a block
    with a named error, not a bare stack() shape complaint."""
    runner, _ = _session()
    small = {k: v[: BATCH // 2] for k, v in _batch_fn(1).items()}
    with pytest.raises(ValueError, match="uniformly-shaped"):
        runner.shard_block([_batch_fn(0), small])


def test_async_runner_rejects_run_many():
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(
        _loss, _params(), optax.sgd(0.1), example_batch=_batch_fn(0))
    assert not runner.supports_run_many
    with pytest.raises(RuntimeError, match="async"):
        runner.run_many(None, [_batch_fn(0)])


# --------------------------------------------------------------- train(unroll=)

def _runner():
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(_loss, _params(), optax.adam(1e-2),
                                         example_batch=_batch_fn(0))


def test_train_unrolled_matches_per_step():
    per_step = train(_runner(), _params(), _batch_fn, steps=10, log_every=0)
    fused = train(_runner(), _params(), _batch_fn, steps=10, log_every=0,
                  unroll=4)  # blocks of 4, 4, 2 — steps cap clips the last
    assert int(fused.step) == 10
    _assert_trees_equal(fused.params, per_step.params)


def test_train_unrolled_partial_final_block_on_exhaustion():
    """An iterator that ends mid-block runs the partial remainder and stops
    with exact step accounting."""
    per_step = train(_runner(), _params(), [_batch_fn(i) for i in range(5)],
                     steps=100, log_every=0)
    fused = train(_runner(), _params(), [_batch_fn(i) for i in range(5)],
                  steps=100, log_every=0, unroll=4)  # blocks of 4 then 1
    assert int(fused.step) == 5
    _assert_trees_equal(fused.params, per_step.params)


def test_train_unrolled_resume_mid_run(tmp_path):
    """Save cadence points force block boundaries, so an interrupted unrolled
    run resumes at the same step a per-step run would — and lands on the same
    final state."""
    direct = train(_runner(), _params(), _batch_fn, steps=10, log_every=0)

    ckpt = str(tmp_path / "ckpts")
    first = train(_runner(), _params(), _batch_fn, steps=7, log_every=0,
                  unroll=4, checkpoint_dir=ckpt, save_every=3)
    assert int(first.step) == 7
    # Periodic saves fired at the per-step cadence (3, 6), final at 7.
    assert Saver.latest_checkpoint(ckpt).endswith("model-7")

    resumed = train(_runner(), _params(), _batch_fn, steps=10, log_every=0,
                    unroll=4, checkpoint_dir=ckpt, save_every=3)
    assert int(resumed.step) == 10
    _assert_trees_equal(resumed.params, direct.params)


def test_train_unrolled_iterator_resume_fast_forwards(tmp_path):
    direct = train(_runner(), _params(), [_batch_fn(i) for i in range(8)],
                   steps=8, log_every=0, unroll=3)
    ckpt = str(tmp_path / "ckpts")
    train(_runner(), _params(), [_batch_fn(i) for i in range(8)], steps=4,
          checkpoint_dir=ckpt, log_every=0, unroll=3)
    resumed = train(_runner(), _params(), [_batch_fn(i) for i in range(8)],
                    steps=8, checkpoint_dir=ckpt, log_every=0, unroll=3)
    assert int(resumed.step) == 8
    _assert_trees_equal(resumed.params, direct.params)


def test_train_unrolled_eval_cadence_unchanged():
    """eval_every boundaries clip blocks, so evals fire at exactly the same
    steps (and on the same params) as the per-step loop."""
    evals = []
    held_out = _batch_fn(999)
    train(_runner(), _params(), _batch_fn, steps=9, log_every=0, unroll=4,
          eval_every=3, eval_batch=held_out,
          on_eval=lambda step, val: evals.append((step, float(val))))
    assert [s for s, _ in evals] == [3, 6, 9]
    assert evals[-1][1] < evals[0][1]


def test_train_unrolled_metrics_fire_at_block_granularity():
    """Block mode logs at the first block end with >= log_every post-warmup
    steps (the first block is warmup); losses sync only at those boundaries."""
    seen = []
    train(_runner(), _params(), _batch_fn, steps=8, log_every=3, unroll=4,
          on_metrics=lambda step, loss, rate: seen.append((step, loss, rate)))
    # Block 1 (steps 1-4) is warmup; block 2 ends at step 8 with 4 >= 3
    # post-warmup steps -> one period.
    assert [s for s, _, _ in seen] == [8]
    assert all(rate > 0 for _, _, rate in seen)
    assert all(np.isfinite(loss) for _, loss, _ in seen)


def test_train_unroll_one_is_per_step_loop():
    """unroll=1 must take today's per-step path (meter boundaries at 1+3k)."""
    seen = []
    train(_runner(), _params(), _batch_fn, steps=7, log_every=3, unroll=1,
          on_metrics=lambda step, loss, rate: seen.append(step))
    assert seen == [4, 7]
