"""Sequence/context parallelism: the full training path over the ``seq`` mesh axis.

Long-context capability beyond the reference (SURVEY.md §5.7: the reference has no
sequence parallelism). The sequence dimension of the batch is sharded over the
``seq`` axis; the model runs per-shard inside one ``jax.shard_map`` with

- globally-offset position embeddings (each shard passes its ring offset to the
  model),
- ring attention for the attention mixing (K/V rotate via ``ppermute``,
  :mod:`autodist_tpu.parallel.ring_attention`), and
- the loss reduced with ``psum`` over data + seq axes so the scalar is the global
  token mean and its gradient psums back automatically through the shard_map
  transpose.

The resulting ``loss_fn(params, batch)`` has the framework's standard signature, so
the normal :class:`~autodist_tpu.runner.DistributedRunner` drives it — sequence
parallelism composes with data parallelism in one mesh. (Gradient compression does
NOT compose: its sync path is itself a shard_map and cannot nest inside the SP
loss's; the SequenceParallel builder rejects compressors at construction.)
"""

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.parallel import plan as plan_lib

_SP_AXES = plan_lib.DP_AXES + (const.MESH_AXIS_SEQ,)


def make_sequence_parallel_loss_fn(model, mesh: Mesh) -> Callable:
    """Build ``loss_fn(params, batch)`` computing next-token cross entropy with the
    sequence dim sharded over the mesh's ``seq`` axis.

    ``model`` must accept ``(tokens, pos_offset=...)`` and use ring attention for
    sequence mixing (``TransformerLMConfig(attention_impl="ring")``); every other
    layer must be positionwise, which is what makes per-shard evaluation exact.
    ``batch = {"tokens": int32 [B, L+1]}`` with B divisible by the data axes and L
    divisible by the seq axis.
    """
    seq_size = mesh.shape.get(const.MESH_AXIS_SEQ, 1)
    tok_spec = P(plan_lib.DP_AXES, const.MESH_AXIS_SEQ)
    max_len = getattr(getattr(model, "config", None), "max_len", None)

    fused_head = bool(getattr(getattr(model, "config", None), "fused_head", False))

    def local_loss(params, inputs, targets):
        l_local = inputs.shape[1]
        offset = jax.lax.axis_index(const.MESH_AXIS_SEQ) * l_local
        if fused_head:
            # Per-shard rows are independent tokens, so the fused pallas
            # head+loss (ops/fused_xent) composes with sequence sharding as-is
            # — each shard scores its own tokens, logits never materialize.
            # One shared definition of the head table/layout lives in
            # transformer_lm.fused_head_nll.
            from autodist_tpu.models.transformer_lm import fused_head_nll
            nll = fused_head_nll(model, params, inputs, targets,
                                 pos_offset=offset)
        else:
            logits = model.apply({"params": params}, inputs, pos_offset=offset)
            logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logprobs, targets[..., None],
                                       axis=-1)[..., 0]
        # Global token mean: psum local sums over every batch/sequence shard.
        total = jax.lax.psum(nll.sum(), _SP_AXES)
        count = jax.lax.psum(jnp.float32(nll.size), _SP_AXES)
        return total / count

    sharded = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec),
        out_specs=P(),
        check_vma=False,
    )

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        # Shift globally BEFORE sharding so targets cross shard boundaries
        # correctly (shard s's last target is shard s+1's first input token).
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        if inputs.shape[1] % seq_size:
            raise ValueError(
                f"Sequence length {inputs.shape[1]} is not divisible by the seq "
                f"axis ({seq_size})")
        if max_len is not None and inputs.shape[1] > max_len:
            # Must be validated globally: per-shard, dynamic_slice would silently
            # CLAMP an out-of-range pos_offset and reuse wrong position embeddings.
            raise ValueError(
                f"Global sequence length {inputs.shape[1]} exceeds the model's "
                f"max_len ({max_len})")
        return sharded(params, inputs, targets)

    return loss_fn


def create_sequence_parallel_session(autodist, model, params, optimizer):
    """Sequence-parallel counterpart of ``AutoDist.create_distributed_session``.

    The SP loss closes over the mesh (its shard_map needs it), so the mesh is
    materialized from the compiled strategy first, then the standard runner drives
    the sharded step. ``autodist`` should carry a strategy with a ``seq`` axis
    (:class:`~autodist_tpu.strategy.SequenceParallel`).
    """
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.plan import ShardingPlan
    from autodist_tpu.runner import DistributedRunner

    model_spec = ModelSpec(params)
    strategy = autodist.build_strategy(model_spec)
    # Multi-node: cluster + workers + jax.distributed (SP is always synchronous).
    autodist._setup(strategy, async_mode=False)
    compiled = autodist._compile(model_spec)
    plan = ShardingPlan.from_strategy(compiled, model_spec)
    mesh = build_mesh(axes=dict(plan.mesh_axes))
    loss_fn = make_sequence_parallel_loss_fn(model, mesh)
    return DistributedRunner(compiled, model_spec, loss_fn, optimizer,
                             mesh=mesh, plan=plan)
