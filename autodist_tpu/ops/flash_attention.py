"""Flash attention — pallas TPU kernels, forward AND backward.

Forward: grid (batch*heads, q-blocks, k-blocks); each K/V block streams through
VMEM via its own BlockSpec while VMEM scratch carries the online-softmax state
(running max, denominator, unnormalized accumulator) across the k dimension of the
grid — the [L, L] score matrix never exists, and resident VMEM is O(q_block +
k_block), independent of sequence length. Causal upper-triangular blocks are
skipped entirely (~2x fewer FLOPs). The per-row logsumexp is emitted as a residual
for the backward pass.

Backward (FlashAttention-2 style): scores are recomputed blockwise from the saved
logsumexp, so nothing quadratic is ever materialized. Two kernels:

- dK/dV: grid (batch*heads, k-blocks, q-blocks) — each k block accumulates
  p^T dO and ds^T q across all its query blocks in VMEM scratch.
- dQ:    grid (batch*heads, q-blocks, k-blocks) — each q block accumulates
  ds k across its key blocks.

The row term D_i = rowsum(dO * O) is precomputed in XLA (elementwise, fused).

On non-TPU backends the kernels run in pallas interpret mode, so tests exercise
the same code path on the CPU-sim mesh.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.ops.blockwise_attention import NEG_INF

# 512-blocks amortize grid/DMA overhead into MXU-sized matmuls: measured on a TPU
# v5e chip (B=8 H=8 D=64, causal, fwd+bwd) flash@512 beats XLA's fused dot-product
# attention at L>=2048 (10.1 vs 10.9 ms) and 1.5x at L=4096 (21.7 vs 32.5 ms),
# while 128-blocks were 2.5x SLOWER than XLA. 1024 is faster still (16 ms at
# L=4096) at higher VMEM pressure — worth passing explicitly for long context.
DEFAULT_Q_BLOCK = 512
DEFAULT_K_BLOCK = 512
_LANES = 128  # scratch minor dim (TPU lane count)


def _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                         q_start, k_start, q_off, k_off, lk, causal, scale):
    """One k-block online-softmax update against the VMEM-resident (acc, m, l)
    state — the single definition shared by the plain forward kernel and the
    carry variant. Matmul operands stay in the input dtype (bf16 runs the MXU at
    full rate); accumulation and softmax arithmetic are f32."""
    q = q_ref[0]                                      # [bq, d]
    k_blk = k_ref[0]                                  # [bk, d]
    v_blk = v_ref[0]
    bq, bk = q.shape[0], k_blk.shape[0]
    scores = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [bq, bk]
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    invalid = k_pos >= lk                             # tail padding (local)
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        invalid = invalid | (k_off + k_pos > q_off + q_pos)
    scores = jnp.where(invalid, NEG_INF, scores)

    m_prev = m_ref[:, :1]                             # [bq, 1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
    correction = jnp.exp(m_prev - m_new)
    p = jnp.where(scores <= NEG_INF * 0.5, 0.0, jnp.exp(scores - m_new))
    l_ref[:] = jnp.broadcast_to(l_prev * correction + p.sum(axis=-1, keepdims=True),
                                l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _flash_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *,
                  lk: int, q_block: int, k_block: int, causal: bool, scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    # Global offsets of the first local query/key (SMEM scalars): ring attention
    # passes the ring-shifted key offset so causal masking stays globally correct;
    # the plain path passes zeros.
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * q_block
    k_start = ki * k_block
    # Causal: skip blocks strictly above the (global) diagonal.
    needed = (k_off + k_start <= q_off + q_start + q_block - 1) if causal else True

    @pl.when(needed)
    def _step():
        _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
                             q_start, k_start, q_off, k_off, lk, causal, scale)

    @pl.when(ki == n_k - 1)
    def _finish():
        l_fin = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_fin, 1e-30)).astype(o_ref.dtype)
        # Per-row logsumexp residual for the backward pass. Padding query rows get
        # a finite lse too (zero-padded q still attends real keys); the backward is
        # safe for them ONLY because dO is zero-padded there — do not rely on lse
        # being NEG_INF for masked rows. Layout: [bh, n_q, bq] with the whole
        # (n_q, bq) plane as one resident block (TPU tiling forbids a [1, bq]
        # block); each q-block writes its row.
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))
        lse_ref[0, qi, :] = lse


def _flash_forward(q, k, v, causal: bool, q_block: int, k_block: int,
                   interpret: bool):
    """Returns (out [B, Lq, H, D], lse [B*H, n_q, bq] f32)."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = 1.0 / (d ** 0.5)

    # Collapse (batch, head) into the grid's first axis: [B*H, L, D].
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)

    bq = min(q_block, lq)
    n_q = pl.cdiv(lq, bq)
    if n_q * bq - lq:
        qf = jnp.pad(qf, ((0, 0), (0, n_q * bq - lq), (0, 0)))
    bk = min(k_block, lk)
    n_k = pl.cdiv(lk, bk)
    if n_k * bk - lk:
        kf = jnp.pad(kf, ((0, 0), (0, n_k * bk - lk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, n_k * bk - lk), (0, 0)))

    kernel = functools.partial(_flash_kernel, lk=lk, q_block=bq, k_block=bk,
                               causal=causal, scale=scale)
    offs = jnp.zeros((2,), jnp.int32)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            # VMEM bound: the whole [n_q, bq] lse plane (one f32 row per query,
            # ~4*Lq bytes) stays resident per grid row in this kernel and both
            # backward kernels, so max single-shard sequence length is capped at
            # roughly VMEM/4 bytes minus block working set — ~1M tokens/shard on
            # 16MB VMEM parts, far beyond the q/k block working set that binds
            # first in practice. Restructure to a per-q-block [bq, LANES] scratch
            # staged out per block if shards ever approach that.
            pl.BlockSpec((1, n_q, bq), lambda bh, i, j: (bh, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, n_q * bq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, n_q, bq), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),       # acc
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running denominator
        ],
        interpret=interpret,
    )(offs, qf, kf, vf)

    out = out[:, :lq, :].reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    return out, lse


def _recompute_p_ds(q, do, k_blk, v_blk, lse, dd, q_start, k_start, lk, causal,
                    scale, q_off=0, k_off=0):
    """Shared backward block math: p [bq, bk] and ds (pre-scale) from a recomputed
    score block. Matmul operands keep the input dtype (MXU rate); p/ds are f32."""
    bq, bk = q.shape[0], k_blk.shape[0]
    scores = scale * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    invalid = k_pos >= lk
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        invalid = invalid | (k_off + k_pos > q_off + q_pos)
    p = jnp.where(invalid, 0.0, jnp.exp(scores - lse))            # [bq, bk]
    dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bq, bk]
    ds = p * (dp - dd)
    return p, ds


def _flash_bwd_dkdv_kernel(off_ref, q_ref, do_ref, lse_ref, dd_ref, k_ref, v_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *,
                           lk: int, q_block: int, k_block: int, causal: bool,
                           scale: float):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * q_block
    k_start = ki * k_block
    needed = (k_off + k_start <= q_off + q_start + q_block - 1) if causal else True

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        lse = lse_ref[0, qi, :][:, None]                  # [bq, 1]
        dd = dd_ref[0, qi, :][:, None]
        p, ds = _recompute_p_ds(q, do, k_blk, v_blk, lse, dd, q_start, k_start,
                                lk, causal, scale, q_off, k_off)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(off_ref, q_ref, do_ref, lse_ref, dd_ref, k_ref, v_ref,
                         dq_ref, dq_acc, *,
                         lk: int, q_block: int, k_block: int, causal: bool,
                         scale: float):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * q_block
    k_start = ki * k_block
    needed = (k_off + k_start <= q_off + q_start + q_block - 1) if causal else True

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        do = do_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        lse = lse_ref[0, qi, :][:, None]
        dd = dd_ref[0, qi, :][:, None]
        _, ds = _recompute_p_ds(q, do, k_blk, v_blk, lse, dd, q_start, k_start,
                                lk, causal, scale, q_off, k_off)
        dq_acc[:] += scale * jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def prepare_backward_q_side(q, o, g, q_block):
    """Query-side backward layout: transposed/padded q and dO plus the row term
    D_i = rowsum(dO * O) in the kernels' [bh, n_q, bq] plane layout. Depends only
    on the query side, so ring attention computes it ONCE and reuses it across
    every ring step."""
    b, lq, h, d = q.shape
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    dof = g.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    of = o.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    # D_i = rowsum(dO * O) — elementwise, XLA fuses it.
    dd = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)

    bq = min(q_block, lq)
    n_q = pl.cdiv(lq, bq)
    q_pad = n_q * bq - lq
    if q_pad:
        qf = jnp.pad(qf, ((0, 0), (0, q_pad), (0, 0)))
        dof = jnp.pad(dof, ((0, 0), (0, q_pad), (0, 0)))   # zero dO kills pad rows
        dd = jnp.pad(dd, ((0, 0), (0, q_pad)))
    dd = dd.reshape(b * h, n_q, bq)                        # lse's [bh, n_q, bq] layout
    return qf, dof, dd, bq, n_q


def _flash_backward_kv(qf, dof, lse, dd, k, v, causal, bq, n_q, k_block,
                       interpret, q_shape, q_offset=0, k_offset=0,
                       out_dtype=None):
    """Backward against one K/V shard from prepared query-side layout. Returns
    (dq, dk, dv) in [B, L, H, D]; ``out_dtype`` overrides the kernels' output
    dtype (ring passes f32 so per-step contributions accumulate unquantized)."""
    b, lq, h, d = q_shape
    lk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)])

    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    bk = min(k_block, lk)
    n_k = pl.cdiv(lk, bk)
    k_pad = n_k * bk - lk
    if k_pad:
        kf = jnp.pad(kf, ((0, 0), (0, k_pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, k_pad), (0, 0)))
    dq_dtype = out_dtype or qf.dtype
    dk_dtype = out_dtype or k.dtype
    dv_dtype = out_dtype or v.dtype

    q_spec = pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((1, n_q, bq), lambda bh, i, j: (bh, 0, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, i, 0))

    dkdv_kernel = functools.partial(
        _flash_bwd_dkdv_kernel, lk=lk, q_block=bq, k_block=bk, causal=causal,
        scale=scale)
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=(b * h, n_k, n_q),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec, q_spec, row_spec, row_spec, kv_spec, kv_spec],
        out_specs=(
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, n_k * bk, d), dk_dtype),
            jax.ShapeDtypeStruct((b * h, n_k * bk, d), dv_dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(offs, qf, dof, lse, dd, kf, vf)

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, lk=lk, q_block=bq, k_block=bk, causal=causal,
        scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, n_q, bq), lambda bh, i, j: (bh, 0, 0)),
            pl.BlockSpec((1, n_q, bq), lambda bh, i, j: (bh, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, n_q * bq, d), dq_dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(offs, qf, dof, lse, dd, kf, vf)

    dq = dq[:, :lq, :].reshape(b, h, lq, d).transpose(0, 2, 1, 3)
    dk = dk[:, :lk, :].reshape(b, h, lk, d).transpose(0, 2, 1, 3)
    dv = dv[:, :lk, :].reshape(b, h, lk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv


def _flash_backward(q, k, v, o, lse, g, causal, q_block, k_block, interpret,
                    q_offset=0, k_offset=0, out_dtype=None):
    qf, dof, dd, bq, n_q = prepare_backward_q_side(q, o, g, q_block)
    return _flash_backward_kv(qf, dof, lse, dd, k, v, causal, bq, n_q, k_block,
                              interpret, q.shape, q_offset=q_offset,
                              k_offset=k_offset, out_dtype=out_dtype)


def _flash_carry_kernel(off_ref, q_ref, k_ref, v_ref, acc_in_ref, m_in_ref,
                        l_in_ref, acc_out_ref, m_out_ref, l_out_ref,
                        acc_sc, m_sc, l_sc, *,
                        lk: int, q_block: int, k_block: int, causal: bool,
                        scale: float):
    """Forward kernel with online-softmax carry in/out (ring attention's local
    step): identical block math to :func:`_flash_kernel`, but the (acc, m, l)
    state initializes from the carry inputs and is emitted UNNORMALIZED so
    partial results merge across ring steps (the scratch-carried state IS the
    ring merge state — no extra merge pass needed)."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)
    q_off = off_ref[0]
    k_off = off_ref[1]

    @pl.when(ki == 0)
    def _init():
        acc_sc[:] = acc_in_ref[0]
        m_sc[:] = jnp.broadcast_to(m_in_ref[0, qi, :][:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_in_ref[0, qi, :][:, None], l_sc.shape)

    q_start = qi * q_block
    k_start = ki * k_block
    needed = (k_off + k_start <= q_off + q_start + q_block - 1) if causal else True

    @pl.when(needed)
    def _step():
        _online_softmax_step(q_ref, k_ref, v_ref, acc_sc, m_sc, l_sc,
                             q_start, k_start, q_off, k_off, lk, causal, scale)

    @pl.when(ki == n_k - 1)
    def _finish():
        acc_out_ref[0] = acc_sc[:]
        m_out_ref[0, qi, :] = m_sc[:, 0]
        l_out_ref[0, qi, :] = l_sc[:, 0]


def flash_attention_with_carry(q, k, v, carry=None, *, causal: bool = True,
                               q_offset=0, k_offset=0,
                               q_block: int = DEFAULT_Q_BLOCK,
                               k_block: int = DEFAULT_K_BLOCK,
                               interpret=None):
    """Pallas ring-attention local step: (acc, m, l) carry in/out.

    Same carry layout as :func:`blockwise_attention_with_carry` — acc
    [B, H, Lq, D] f32 unnormalized, m/l [B, H, Lq] f32 — so ring attention can
    use either implementation interchangeably; normalize with
    ``blockwise_attention.finalize``. ``q_offset``/``k_offset`` may be traced
    (ring step indices); they enter the kernel as SMEM scalars.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = _use_interpret()

    qf = q.transpose(0, 2, 1, 3).reshape(b * h, lq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, lk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, lk, d)

    bq = min(q_block, lq)
    n_q = pl.cdiv(lq, bq)
    q_pad = n_q * bq - lq
    if q_pad:
        qf = jnp.pad(qf, ((0, 0), (0, q_pad), (0, 0)))
    bk = min(k_block, lk)
    n_k = pl.cdiv(lk, bk)
    if n_k * bk - lk:
        kf = jnp.pad(kf, ((0, 0), (0, n_k * bk - lk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, n_k * bk - lk), (0, 0)))

    if carry is None:
        acc0 = jnp.zeros((b * h, n_q * bq, d), jnp.float32)
        m0 = jnp.full((b * h, n_q, bq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b * h, n_q, bq), jnp.float32)
    else:
        acc_c, m_c, l_c = carry
        acc0 = acc_c.reshape(b * h, lq, d).astype(jnp.float32)
        m0 = m_c.reshape(b * h, lq).astype(jnp.float32)
        l0 = l_c.reshape(b * h, lq).astype(jnp.float32)
        if q_pad:
            acc0 = jnp.pad(acc0, ((0, 0), (0, q_pad), (0, 0)))
            m0 = jnp.pad(m0, ((0, 0), (0, q_pad)), constant_values=NEG_INF)
            l0 = jnp.pad(l0, ((0, 0), (0, q_pad)))
        m0 = m0.reshape(b * h, n_q, bq)
        l0 = l0.reshape(b * h, n_q, bq)

    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32)])
    kernel = functools.partial(_flash_carry_kernel, lk=lk, q_block=bq, k_block=bk,
                               causal=causal, scale=scale)
    row_plane = pl.BlockSpec((1, n_q, bq), lambda bh, i, j: (bh, 0, 0))
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            row_plane,
            row_plane,
        ],
        out_specs=(
            pl.BlockSpec((1, bq, d), lambda bh, i, j: (bh, i, 0)),
            row_plane,
            row_plane,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * h, n_q * bq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, n_q, bq), jnp.float32),
            jax.ShapeDtypeStruct((b * h, n_q, bq), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(offs, qf, kf, vf, acc0, m0, l0)

    acc = acc[:, :lq, :].reshape(b, h, lq, d)
    m = m.reshape(b * h, n_q * bq)[:, :lq].reshape(b, h, lq)
    l = l.reshape(b * h, n_q * bq)[:, :lq].reshape(b, h, lq)
    return acc, m, l


def _use_interpret() -> bool:
    # The axon tunnel registers TPU devices under the 'axon' platform name; both it
    # and plain 'tpu' take the Mosaic path. Everything else interprets.
    return jax.default_backend() not in ("tpu", "axon")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, q_block, k_block):
    out, _ = _flash_forward(q, k, v, causal, q_block, k_block, _use_interpret())
    return out


def _flash_fwd(q, k, v, causal, q_block, k_block):
    out, lse = _flash_forward(q, k, v, causal, q_block, k_block, _use_interpret())
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, q_block, k_block, residuals, g):
    q, k, v, o, lse = residuals
    return _flash_backward(q, k, v, o, lse, g, causal, q_block, k_block,
                           _use_interpret())


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, q_block: int = DEFAULT_Q_BLOCK,
                    k_block: int = DEFAULT_K_BLOCK) -> jax.Array:
    """Flash attention over [B, L, H, D] tensors (pallas forward and backward)."""
    return _flash(q, k, v, causal, q_block, k_block)
