"""Fleet metrics plane: history, OpenMetrics exposition, alerts, adfleet.

Covers the PR 11 contract end to end (docs/usage/observability.md "Metric
history" / "OpenMetrics endpoint" / "Alert rules" / "Fleet console"):

- OpenMetrics/Prometheus text rendering round-trips through a SELF-CONTAINED
  text-format parser (name sanitization, label escaping, cumulative ``le``
  buckets, counter ``_total`` monotonicity);
- ``MetricsHistory``: ring bound, window/series queries, throttling, JSONL
  shard rotation + retention, the wall-clock sampler thread;
- every alert predicate kind: threshold (+ for-duration coverage), multi-
  window burn rate over histogram-delta quantiles, and the tuned-plan drift
  band (``ref_from="plan"`` against the applied plan's predicted breakdown,
  ``ref_from="window_max"`` MFU collapse);
- rule loading from file/inline JSON with same-name override and malformed-
  rule degradation (warn + skip, never crash the sampling loop);
- the END-TO-END acceptance pin: an injected data-loader stall inside
  ``train()`` drifts ``train.attr.data_wait`` past the SHIPPED rule's band ->
  the alert event fires -> a flight-recorder snapshot lands with the alert in
  its manifest -> the same process's ``/metrics`` endpoint exposes the
  ``alert_active`` gauge — NO human action anywhere;
- ``/metrics`` + ``/healthz`` over loopback HTTP;
- ``tools/adfleet.py --once/--raw`` against two loopback ``status`` servers
  (one PS kind, one serve kind) with fleet-aggregated quantiles;
- the shared quantile helper and the new flag registrations.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window.
"""

import importlib.util
import json
import math
import os
import re
import time
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const, telemetry, train  # noqa: E402
from autodist_tpu.strategy import AllReduce  # noqa: E402
from autodist_tpu.telemetry import (alerts, history, metrics,  # noqa: E402
                                    openmetrics, profiling, recorder)


@pytest.fixture(autouse=True)
def _plane_reset():
    """Leave the process-global planes as found: no history, no engine, no
    exporter, no recorder, empty span/event rings (instruments stay — the
    registry is additive-only and shared across the suite)."""
    def reset():
        history.set_history(None)
        alerts.set_engine(None)
        openmetrics.set_exporter(None)
        recorder.set_recorder(None)
        profiling.set_applied_plan(None)
        profiling.disable()
        telemetry.disable()
        telemetry.clear()
        telemetry.registry().clear_events()
    reset()
    yield
    reset()


def _fresh_registry():
    return metrics.Registry()


def _mk_history(engine=False, **kw):
    kw.setdefault("out_dir", "")
    kw.setdefault("min_interval_s", 0.0)
    return history.MetricsHistory(engine=engine, **kw)


# ------------------------------------------------- OpenMetrics text format

def _parse_exposition(text: str):
    """A SELF-CONTAINED Prometheus text-format 0.0.4 parser: returns
    ({name: type}, {(name, frozenset(labels)): value}). Raises on any line
    the format does not allow — the round-trip test doubles as the
    "standard-format scrape parses clean" acceptance pin."""
    types, samples = {}, {}
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(None, 3)
            assert mtype in ("counter", "gauge", "histogram", "summary")
            types[name] = mtype
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP "), f"bad comment line: {line!r}"
            continue
        m = re.match(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$',
                     line)
        assert m, f"unparseable sample line: {line!r}"
        name, labelstr, value = m.groups()
        assert name_re.match(name)
        labels = frozenset()
        if labelstr:
            pairs = re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                               labelstr)
            labels = frozenset(pairs)
        v = float(value.replace("+Inf", "inf").replace("-Inf", "-inf"))
        samples[(name, labels)] = v
    return types, samples


def test_openmetrics_roundtrip_counters_gauges():
    reg = _fresh_registry()
    reg.counter("ps.wire.bytes_sent").inc(1234)
    reg.gauge("train.mfu").set(0.283)
    reg.gauge("alert.active").set(2)
    types, samples = _parse_exposition(openmetrics.render(reg))
    assert types["ps_wire_bytes_sent_total"] == "counter"
    assert samples[("ps_wire_bytes_sent_total", frozenset())] == 1234
    assert types["train_mfu"] == "gauge"
    assert samples[("train_mfu", frozenset())] == 0.283
    assert samples[("alert_active", frozenset())] == 2


def test_openmetrics_histogram_cumulative_le_buckets():
    reg = _fresh_registry()
    h = reg.histogram("serve.latency_s.total", buckets=(0.1, 0.5, 1.0))
    for v in (0.05, 0.05, 0.3, 2.0):
        h.observe(v)
    types, samples = _parse_exposition(openmetrics.render(reg))
    name = "serve_latency_s_total"
    assert types[name] == "histogram"
    # Buckets are CUMULATIVE (the registry's snapshot form is per-bucket —
    # the renderer must convert or every scraper misreads the histogram).
    assert samples[(name + "_bucket", frozenset({("le", "0.1")}))] == 2
    assert samples[(name + "_bucket", frozenset({("le", "0.5")}))] == 3
    assert samples[(name + "_bucket", frozenset({("le", "1")}))] == 3
    assert samples[(name + "_bucket", frozenset({("le", "+Inf")}))] == 4
    assert samples[(name + "_count", frozenset())] == 4
    assert samples[(name + "_sum", frozenset())] == pytest.approx(2.4)


def test_openmetrics_counter_monotonicity_and_name_sanitization():
    reg = _fresh_registry()
    c = reg.counter("weird-name.with spaces.9lead")
    c.inc(1)
    text1 = openmetrics.render(reg)
    c.inc(2)
    text2 = openmetrics.render(reg)
    _, s1 = _parse_exposition(text1)
    types, s2 = _parse_exposition(text2)
    key = [k for k in s1 if k[0].endswith("_total")]
    assert len(key) == 1   # one sanitized counter, a legal exposition name
    assert s2[key[0]] >= s1[key[0]]   # counters only go up
    assert types[key[0][0]] == "counter"


def test_openmetrics_escaping_and_special_values():
    reg = _fresh_registry()
    reg.gauge("g.inf").set(float("inf"))
    reg.gauge("g.nan").set(float("nan"))
    types, samples = _parse_exposition(openmetrics.render(reg))
    assert samples[("g_inf", frozenset())] == float("inf")
    assert math.isnan(samples[("g_nan", frozenset())])
    assert openmetrics._escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert openmetrics._escape_help("x\ny") == "x\\ny"


# ----------------------------------------------------------- shared quantile

def test_quantile_interpolates_and_bounds():
    h = metrics.Histogram("q", buckets=(0.1, 0.2, 0.4))
    for v in [0.05] * 50 + [0.15] * 40 + [0.3] * 10:
        h.observe(v)
    snap = h.snapshot()
    assert metrics.quantile(snap, 0.5) == pytest.approx(0.1)
    # p99 lands in the (0.2, 0.4] bucket, nine-tenths in: interpolated.
    assert metrics.quantile(snap, 0.99) == pytest.approx(0.38)
    # The +inf bucket answers with the largest finite edge (a LOWER bound).
    h.observe(100.0)
    assert metrics.quantile(h.snapshot(), 1.0) == pytest.approx(0.4)
    assert metrics.quantile({}, 0.5) is None
    assert metrics.quantile({"count": 0}, 0.5) is None
    assert metrics.quantile(3.0, 0.5) is None    # not a histogram
    # adtop's SLO path delegates here — the consoles and the alert engine
    # can never drift on what p99 means.
    spec = importlib.util.spec_from_file_location(
        "adtop_q", os.path.join(os.path.dirname(__file__), os.pardir,
                                "tools", "adtop.py"))
    ad = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ad)
    assert ad._hist_quantile(snap, 0.5) == metrics.quantile(snap, 0.5)


def test_merge_histograms_sums_elementwise():
    a = {"le:0.1": 2, "le:+inf": 1, "count": 3, "sum": 0.5}
    b = {"le:0.1": 1, "le:+inf": 0, "count": 1, "sum": 0.05}
    merged = metrics.merge_histograms([a, b, "not-a-dict"])
    assert merged == {"le:0.1": 3, "le:+inf": 1, "count": 4, "sum": 0.55}


# ------------------------------------------------------------ metric history

def test_history_ring_bound_and_series():
    g = telemetry.gauge("mp.test.gauge")
    h = _mk_history(ring=4)
    for i in range(7):
        g.set(i)
        h.sample(step=i)
    samples = h.samples()
    assert len(samples) == 4                      # ring bound
    assert [s["step"] for s in samples] == [3, 4, 5, 6]
    series = h.series("mp.test.gauge")
    assert [v for _, v in series] == [3, 4, 5, 6]
    assert h.latest()["metrics"]["mp.test.gauge"] == 6
    assert h.window(10_000.0)[-1]["step"] == 6


def test_history_maybe_sample_throttles():
    h = _mk_history(min_interval_s=3600.0)
    assert h.maybe_sample(step=1) is not None
    assert h.maybe_sample(step=2) is None          # inside the window
    assert h.sample(step=3) is not None            # sample() always samples
    assert len(h.samples()) == 2


def test_history_jsonl_shards_rotate_and_retain(tmp_path):
    d = str(tmp_path / "metrics")
    h = _mk_history(out_dir=d, shard_lines=2, keep_shards=2)
    telemetry.gauge("mp.shard.gauge").set(1.25)
    for i in range(7):
        h.sample(step=i)
    shards = h.shards()
    # 7 samples at 2 lines/shard = 4 shards written, latest-2 retained.
    assert len(shards) == 2
    loaded = [rec for p in shards for rec in history.load_history_jsonl(p)]
    assert [rec["step"] for rec in loaded] == [4, 5, 6]
    assert loaded[-1]["metrics"]["mp.shard.gauge"] == 1.25
    assert loaded[-1]["t_wall_s"] > 0
    # A restarted process EXTENDS the numbering instead of clobbering.
    h2 = _mk_history(out_dir=d, shard_lines=2, keep_shards=2)
    h2.sample(step=99)
    assert history.load_history_jsonl(h2.shards()[-1])[0]["step"] == 99
    assert len(set(h.shards()) | set(h2.shards())) >= 2
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"no": "metrics key"}\n')
    with pytest.raises(ValueError, match="sample record"):
        history.load_history_jsonl(str(bad))


def test_history_wall_clock_thread_samples(tmp_path):
    h = _mk_history(min_interval_s=0.0)
    h.start_thread(interval_s=0.1)
    try:
        deadline = time.monotonic() + 5.0
        while not h.samples() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.samples(), "wall-clock sampler produced no sample in 5s"
        assert h.samples()[0]["reason"] == "timer"
    finally:
        h.close()
    n = len(h.samples())
    time.sleep(0.3)
    assert len(h.samples()) == n                   # close() stopped the beat


def test_history_env_arming_and_noop(tmp_path, monkeypatch):
    # Unarmed: maybe_sample is a no-op and installs nothing.
    monkeypatch.delenv("AUTODIST_METRICS_DIR", raising=False)
    monkeypatch.delenv("AUTODIST_ALERT_RULES", raising=False)
    monkeypatch.delenv("AUTODIST_METRICS_INTERVAL_S", raising=False)
    history.set_history(None)
    assert history.maybe_sample(step=1) is None
    assert history.get_history() is None
    # AUTODIST_METRICS_DIR arms on the next call after a reset.
    monkeypatch.setenv("AUTODIST_METRICS_DIR", str(tmp_path / "hist"))
    monkeypatch.setenv("AUTODIST_METRICS_INTERVAL_S", "0")
    history.set_history(None)
    rec = history.maybe_sample(step=2, force=True)
    assert rec is not None and rec["step"] == 2
    h = history.get_history()
    assert h is not None and h.shards()


# ------------------------------------------------------------ alert predicates

def test_threshold_predicate_and_wildcard_selector():
    telemetry.gauge("mp.w.last_seen_s.w0").set(3.0)
    telemetry.gauge("mp.w.last_seen_s.w1").set(200.0)
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="stalled", kind="threshold", metric="mp.w.last_seen_s.*",
        op=">", value=120.0)], action="warn")
    h = _mk_history()
    fired = eng.evaluate(_sampled(h))
    assert [f["rule"] for f in fired] == ["stalled"]
    assert fired[0]["value"] == 200.0              # the WORST worker
    # Recovery auto-resolves and lands in the resolved ring.
    telemetry.gauge("mp.w.last_seen_s.w1").set(1.0)
    assert eng.evaluate(_sampled(h)) == []
    snap = eng.snapshot()
    assert snap["active"] == []
    assert [r["rule"] for r in snap["resolved"]] == ["stalled"]
    assert telemetry.gauge("alert.active.stalled").value == 0
    assert telemetry.gauge("alert.active").value == 0


def _sampled(h, step=None):
    h.sample(step=step)
    return h


def test_threshold_for_duration_needs_history_coverage():
    g = telemetry.gauge("mp.for.gauge")
    g.set(10.0)
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="sustained", kind="threshold", metric="mp.for.gauge",
        op=">", value=5.0, for_s=0.2)], action="warn")
    h = _mk_history()
    # One fresh sample proves nothing about duration: no firing.
    assert eng.evaluate(_sampled(h)) == []
    time.sleep(0.25)
    # Old-enough agreeing history: fires now.
    fired = eng.evaluate(_sampled(h))
    assert [f["rule"] for f in fired] == ["sustained"]
    # A dip inside the window blocks the NEXT evaluation cycle.
    eng2 = alerts.AlertEngine(rules=eng.rules, action="warn")
    h2 = _mk_history()
    h2.sample()
    g.set(0.0)
    h2.sample()
    g.set(10.0)
    time.sleep(0.25)
    assert eng2.evaluate(_sampled(h2)) == []       # the dip is in-window


def test_burn_rate_fires_on_both_windows_and_resolves():
    hist_m = telemetry.histogram("mp.burn.latency_s", buckets=(0.1, 1.0, 5.0))
    rule = alerts.AlertRule(name="p99burn", kind="burn_rate",
                            metric="mp.burn.latency_s", q=0.99,
                            objective_s=1.0, long_s=1.2, short_s=0.6)
    eng = alerts.AlertEngine(rules=[rule], action="warn")
    h = _mk_history()
    h.sample()                                     # window-opening baseline
    for _ in range(50):
        hist_m.observe(4.0)                        # bad traffic from t0...
    time.sleep(0.3)
    # ...but the LONG window has no coverage yet (span ~0.3 < 0.5 * 1.2):
    # a 20-second-old process must not page its "5 minute" burn rate.
    assert eng.evaluate(_sampled(h)) == []
    for _ in range(50):
        hist_m.observe(4.0)                        # the incident continues
    time.sleep(0.3)
    fired = eng.evaluate(_sampled(h))              # both windows covered now
    assert [f["rule"] for f in fired] == ["p99burn"]
    assert fired[0]["p99_long_s"] > 1.0 and fired[0]["p99_short_s"] > 1.0
    # Traffic recovers: once the SHORT window has aged past the incident its
    # delta goes healthy and the alert auto-resolves — even though the LONG
    # window still remembers the bad quantile (the multi-window point: the
    # long side proves budget burned, the short side proves it stopped).
    time.sleep(0.65)                               # age past short_s
    h.sample()                                     # post-incident baseline
    for _ in range(500):
        hist_m.observe(0.05)
    time.sleep(0.3)
    assert eng.evaluate(_sampled(h)) == []
    assert eng.snapshot()["active"] == []
    assert [r["rule"] for r in eng.snapshot()["resolved"]] == ["p99burn"]


def test_drift_band_against_applied_plan():
    profiling.set_applied_plan({
        "cache_key": "k", "knobs": {"unroll": 4},
        "predicted": {"step_s": 0.010, "bound": "compute",
                      "breakdown": {"compute_s": 0.008, "comm_s": 0.001,
                                    "host_s": 0.001}}})
    rule = alerts.AlertRule(name="dw_drift", kind="drift",
                            metric="train.attr.data_wait", ref_from="plan",
                            band=0.25, direction="above")
    eng = alerts.AlertEngine(rules=[rule], action="warn")
    h = _mk_history()
    g = telemetry.gauge("train.attr.data_wait")
    g.set(0.10)                                    # inside the band (ref 0)
    assert eng.evaluate(_sampled(h)) == []
    g.set(0.60)                                    # the stall: 0.6 > 0+0.25
    fired = eng.evaluate(_sampled(h))
    assert [f["rule"] for f in fired] == ["dw_drift"]
    assert fired[0]["bound"] == 0.0 and fired[0]["band"] == 0.25
    # comm drifts against its PREDICTED share (0.001/0.010 = 10%), not 0.
    rule2 = alerts.AlertRule(name="comm_drift", kind="drift",
                             metric="train.attr.comm", ref_from="plan",
                             band=0.2, direction="above")
    eng2 = alerts.AlertEngine(rules=[rule2], action="warn")
    h2 = _mk_history()
    gc = telemetry.gauge("train.attr.comm")
    gc.set(0.25)                                   # 0.25 - 0.1 < 0.2
    assert eng2.evaluate(_sampled(h2)) == []
    gc.set(0.35)                                   # 0.35 - 0.1 > 0.2
    assert [f["rule"] for f in eng2.evaluate(_sampled(h2))] == ["comm_drift"]
    # With NO plan applied the plan-referenced rule is inert, never wrong.
    profiling.set_applied_plan(None)
    eng3 = alerts.AlertEngine(rules=[rule], action="warn")
    h3 = _mk_history()
    assert eng3.evaluate(_sampled(h3)) == []


def test_drift_window_max_mfu_collapse():
    rule = alerts.AlertRule(name="mfu_collapse", kind="drift",
                            metric="train.mfu", ref_from="window_max",
                            window_s=600.0, band=0.5, relative=True,
                            direction="below")
    eng = alerts.AlertEngine(rules=[rule], action="warn")
    h = _mk_history()
    g = telemetry.gauge("train.mfu")
    for v in (0.40, 0.42, 0.41):
        g.set(v)
        h.sample()
    assert eng.evaluate(h) == []                   # healthy plateau
    g.set(0.10)                                    # collapse: < 0.5 * 0.42
    fired = eng.evaluate(_sampled(h))
    assert [f["rule"] for f in fired] == ["mfu_collapse"]
    assert fired[0]["bound"] == pytest.approx(0.42)


# ---------------------------------------------------- rule loading + actions

def test_load_rules_defaults_file_inline_and_degradation(tmp_path, caplog):
    # Shipped defaults alone.
    base = alerts.load_rules("")
    names = {r.name for r in base}
    assert {"serve_p99_burn", "data_wait_drift", "worker_stalled",
            "mfu_collapse"} <= names
    # The shipped burn objective must sit STRICTLY below the latency
    # family's top finite bucket edge: the quantile estimator answers at
    # most that edge, so an objective at/above it could never be exceeded
    # and the shipped SLO rule would be dead on arrival.
    burn = next(r for r in base if r.name == "serve_p99_burn")
    assert burn.objective_s < max(metrics.family_buckets(burn.metric))
    # Inline JSON overlays and same-name entries REPLACE defaults.
    inline = json.dumps([{"name": "worker_stalled", "kind": "threshold",
                          "metric": "ps.worker.last_seen_s.*", "op": ">",
                          "value": 33.0},
                         {"name": "extra", "kind": "threshold",
                          "metric": "mp.x", "op": "<", "value": 1.0}])
    rules = {r.name: r for r in alerts.load_rules(inline)}
    assert rules["worker_stalled"].value == 33.0
    assert "extra" in rules and len(rules) == len(base) + 1
    # A file path loads the same way; {"defaults": false} drops the ship set.
    p = tmp_path / "rules.json"
    p.write_text(json.dumps({"defaults": False, "rules": [
        {"name": "only", "kind": "threshold", "metric": "mp.y",
         "op": ">", "value": 0.0}]}))
    only = alerts.load_rules(str(p))
    assert [r.name for r in only] == ["only"]
    # Malformed entries degrade: the bad rule is SKIPPED with a warning, the
    # good ones load, nothing raises (the loop-never-crashes contract).
    mixed = json.dumps([{"name": "bad", "kind": "nonsense", "metric": "m"},
                        {"name": "good", "kind": "threshold", "metric": "m",
                         "op": ">", "value": 1.0},
                        {"name": "worse", "kind": "threshold", "metric": "m",
                         "op": ">", "value": 1.0, "typo_field": 3}])
    loaded = {r.name for r in alerts.load_rules(mixed)}
    assert "good" in loaded and "bad" not in loaded and "worse" not in loaded
    # An unreadable source keeps the shipped defaults.
    fallback = alerts.load_rules(str(tmp_path / "missing.json"))
    assert {r.name for r in fallback} == {r.name for r in base}


def test_bad_rule_evaluation_never_crashes_sampling():
    class _Boom(alerts.AlertRule):
        def evaluate(self, history):
            raise RuntimeError("boom")
    eng = alerts.AlertEngine(rules=[
        _Boom(name="boom", kind="threshold", metric="m", op=">", value=0.0),
        alerts.AlertRule(name="ok", kind="threshold", metric="mp.ok.gauge",
                         op=">", value=1.0)], action="warn")
    telemetry.gauge("mp.ok.gauge").set(5.0)
    h = _mk_history(engine=eng)
    rec = h.sample()                      # engine runs inside sample()
    assert [f["rule"] for f in eng.active()] == ["ok"]
    assert rec is not None                # the sampling loop survived boom


def test_alert_action_halt_raises_from_sample():
    telemetry.gauge("mp.halt.gauge").set(9.0)
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="h", kind="threshold", metric="mp.halt.gauge", op=">",
        value=1.0)], action="halt")
    h = _mk_history(engine=eng)
    with pytest.raises(alerts.AlertHalt, match="h"):
        h.sample()
    # Everything was booked BEFORE the raise: gauge, event, active record.
    assert telemetry.gauge("alert.active.h").value == 1
    assert [e["name"] for e in telemetry.events()] == ["alert"]
    assert [a["rule"] for a in eng.active()] == ["h"]
    with pytest.raises(ValueError, match="action"):
        alerts.AlertEngine(rules=[], action="explode")


def test_alert_halt_from_train_loop_carries_live_state():
    """action=halt raised at a train() boundary rides with the LIVE
    TrainState attached (the HealthHalt contract: progress stays
    checkpointable, not discarded)."""
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="rate_floor", kind="threshold", metric="train.steps_per_s",
        op=">", value=0.0)], action="halt")
    history.set_history(history.MetricsHistory(
        out_dir="", min_interval_s=0.0, engine=eng))
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(4, 1).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)

    def batches(i):
        return {"x": rng.randn(8, 4).astype(np.float32),
                "y": rng.randn(8, 1).astype(np.float32)}

    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(loss, params, optax.sgd(0.01),
                                           example_batch=batches(0))
    with pytest.raises(alerts.AlertHalt) as exc:
        train(runner, params, batches, steps=8, log_every=2)
    assert exc.value.state is not None
    assert int(exc.value.state.step) > 0          # the live TrainState
    assert exc.value.fired[0]["rule"] == "rate_floor"


def test_alert_record_action_snapshots_through_debounce(tmp_path):
    telemetry.gauge("mp.rec.gauge").set(9.0)
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=4,
                                  min_interval_s=3600.0)
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="r1", kind="threshold", metric="mp.rec.gauge", op=">",
        value=1.0)], action="record", recorder=rec)
    alerts.set_engine(eng)    # the manifest reads the PROCESS engine
    h = _mk_history(engine=eng)
    h.sample()
    snaps = rec.snapshots()
    assert len(snaps) == 1 and "alert.r1" in snaps[0]
    manifest = json.load(open(os.path.join(snaps[0], "manifest.json")))
    assert [a["rule"] for a in manifest["alerts"]] == ["r1"]
    # Re-firing inside the debounce window writes NO second snapshot (the
    # through-the-debounce contract — an alert storm costs one capture).
    telemetry.gauge("mp.rec.gauge").set(0.0)
    h.sample()                                     # resolve
    telemetry.gauge("mp.rec.gauge").set(9.0)
    h.sample()                                     # re-fire
    assert len(rec.snapshots()) == 1


# ------------------------------------------------------- e2e acceptance pin

def test_injected_data_stall_fires_drift_alert_end_to_end(tmp_path):
    """The PR's no-human-in-the-loop proof: a stalling data loader inside a
    REAL train() drifts train.attr.data_wait past the SHIPPED rule's band ->
    the alert event fires at a history boundary -> the flight recorder
    snapshots with the alert in its manifest -> the live /metrics endpoint
    exposes the alert gauge. Nothing here pokes the engine by hand."""
    profiling.enable()
    profiling.reset()
    # The applied plan whose predicted bound the SHIPPED drift rule compares
    # against (data_wait predicted share: 0 — any stall is drift).
    profiling.set_applied_plan({
        "cache_key": "e2e", "knobs": {"unroll": 1},
        "predicted": {"step_s": 0.004, "bound": "compute",
                      "breakdown": {"compute_s": 0.004}}})
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=4,
                                  min_interval_s=0.0)
    recorder.set_recorder(rec)
    eng = alerts.AlertEngine(rules=alerts.load_rules(""), action="warn")
    alerts.set_engine(eng)
    history.set_history(history.MetricsHistory(
        out_dir=str(tmp_path / "hist"), min_interval_s=0.0, engine=eng))
    exporter = openmetrics.MetricsExporter(port=0)
    openmetrics.set_exporter(exporter)
    try:
        rng = np.random.RandomState(0)
        params = {"w": rng.randn(4, 1).astype(np.float32)}

        def loss(p, b):
            return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)

        def batches(i):
            time.sleep(0.012)     # the injected loader stall (~dominant)
            return {"x": rng.randn(8, 4).astype(np.float32),
                    "y": rng.randn(8, 1).astype(np.float32)}

        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(loss, params, optax.sgd(0.01),
                                               example_batch=batches(0))
        train(runner, params, batches, steps=12, log_every=4)

        # 1. the shipped drift rule fired as an `alert` event.
        fired = [e for e in telemetry.events() if e["name"] == "alert"
                 and e.get("rule") == "data_wait_drift"
                 and e.get("state") == "firing"]
        assert fired, f"no data_wait_drift firing in {telemetry.events()}"
        assert fired[0]["value"] > fired[0]["bound"] + fired[0]["band"]
        # 2. the flight recorder snapshotted WITH the alert in its manifest.
        # Other shipped rules may legitimately fire first off gauges earlier
        # suites left in the shared registry (e.g. worker_stalled from a
        # watchdog test's last-seen gauge) — find the drift snapshot, don't
        # assume it won the race for slot 0.
        snaps = [s for s in rec.snapshots() if "alert.data_wait_drift" in s]
        assert snaps, f"no data_wait_drift snapshot in {rec.snapshots()}"
        manifest = json.load(open(os.path.join(snaps[0], "manifest.json")))
        assert any(a["rule"] == "data_wait_drift"
                   for a in manifest["alerts"])
        assert manifest["plan"]["cache_key"] == "e2e"
        # 3. the same process's /metrics exposition carries the alert plane:
        # the per-rule active gauge and the fired counter. The counter is
        # the race-free proof — the end-of-run forced sample re-evaluates
        # the rules on the TAIL period, whose share can legitimately dip
        # back inside the band and auto-resolve the gauge to 0 before this
        # scrape (observed under full-suite load), and an auto-resolve is
        # correct behavior, not a missed alert.
        port = exporter.address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        types, samples = _parse_exposition(body)
        assert ("alert_active_data_wait_drift", frozenset()) in samples
        assert types["alert_active_data_wait_drift"] == "gauge"
        assert samples[("alert_fired_total", frozenset())] >= 1
        assert types["train_attr_data_wait"] == "gauge"
        # 4. the history's JSONL shards retain the drifted series on disk.
        h = history.get_history()
        vals = [v for _, v in h.series("train.attr.data_wait")]
        assert vals and max(vals) > 0.25
        assert h.shards()
    finally:
        profiling.disable()
        profiling.reset()


# --------------------------------------------------- /metrics + /healthz HTTP

def test_metrics_and_healthz_endpoints_over_loopback():
    telemetry.counter("mp.http.requests").inc(7)
    exp = openmetrics.MetricsExporter(port=0)
    try:
        port = exp.address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert body.headers["Content-Type"].startswith("text/plain")
        types, samples = _parse_exposition(body.read().decode())
        assert samples[("mp_http_requests_total", frozenset())] >= 7
        hz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert hz["ok"] is True and hz["uptime_s"] >= 0
        assert hz["alerts_active"] == 0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)
    finally:
        exp.close()


def test_maybe_serve_env_gating(monkeypatch):
    monkeypatch.delenv("AUTODIST_METRICS_PORT", raising=False)
    assert openmetrics.maybe_serve() is None
    monkeypatch.setenv("AUTODIST_METRICS_PORT", "0")
    assert openmetrics.maybe_serve() is None       # "0" stays disabled
    exp = openmetrics.MetricsExporter(port=0)
    openmetrics.set_exporter(exp)
    monkeypatch.setenv("AUTODIST_METRICS_PORT", str(exp.address[1]))
    assert openmetrics.maybe_serve() is exp        # one exporter per process


# ------------------------------------------------------------ fleet console

class _StubPSRunner:
    """The minimal surface PSServer._dispatch drives (the test_health_plane
    pattern): a real gate + numpy-only ParameterService, no compilation."""

    def __init__(self, num_workers=1, staleness=2):
        from autodist_tpu.parallel.staleness import (ParameterService,
                                                     StalenessController)
        from autodist_tpu.runner import TrainState
        state = TrainState(step=np.zeros((), np.int32),
                           params={"w": np.ones((16,), np.float32)},
                           opt_state=(), ef_state=())
        self.service = ParameterService(state, lambda s, grads: s)
        self.controller = StalenessController(num_workers,
                                              staleness=staleness)

    def add_worker(self, worker_id=None, with_generation=False):
        wid, gen = self.controller.register_with_generation(worker_id)
        handle = type("H", (), {"worker_id": wid})()
        return (handle, gen) if with_generation else handle


class _FakeServeEngine:
    capacity = 2

    def admit(self, slot, prompt, key):
        return 0

    def step(self, keys):
        return np.zeros((self.capacity,), np.int32)

    def free(self, slot):
        pass

    def make_keys(self, seed, n):
        return None


def _two_servers():
    from autodist_tpu.parallel.ps_transport import PSServer
    from autodist_tpu.serving.batcher import Batcher, ServeConfig
    from autodist_tpu.serving.transport import InferenceServer
    ps = PSServer(_StubPSRunner(), host="127.0.0.1", watchdog=False)
    batcher = Batcher(_FakeServeEngine(), ServeConfig(max_batch=2),
                      start=False)
    serve = InferenceServer(batcher, host="127.0.0.1", port=0)
    return ps, serve


def _adfleet():
    spec = importlib.util.spec_from_file_location(
        "adfleet_cli", os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools", "adfleet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_adfleet_once_and_raw_against_two_loopback_servers(capsys):
    telemetry.gauge("train.steps_per_s").set(41.5)
    telemetry.gauge("train.mfu").set(0.283)
    lat = telemetry.histogram("serve.latency_s.total")
    for v in (0.002, 0.004, 0.2):
        lat.observe(v)
    telemetry.gauge("mp.fleet.alert_src").set(9.0)
    eng = alerts.AlertEngine(rules=[alerts.AlertRule(
        name="fleet_rule", kind="threshold", metric="mp.fleet.alert_src",
        op=">", value=1.0)], action="warn")
    alerts.set_engine(eng)
    _mk_history(engine=eng).sample()      # one tick: the rule fires
    ps, serve = _two_servers()
    try:
        ps_addr = "%s:%d" % ps.address
        serve_addr = "%s:%d" % serve.address
        fl = _adfleet()
        assert fl.main([ps_addr, serve_addr, "--once"]) == 0
        out = capsys.readouterr().out
        assert "adfleet — 2 endpoint(s)" in out
        assert "ps" in out and "serve" in out
        assert "steps/s   41.50" in out
        assert "mfu  28.3%" in out
        # Fleet aggregation: both endpoints ship the process registry's
        # latency histogram; the merged quantile line renders.
        assert "fleet    serve n=2" in out
        assert "p99" in out
        # The union of active alerts names the rule and the endpoint.
        assert "fleet_rule" in out and "ALERT" in out
        # --raw ships the JSON payload per endpoint.
        assert fl.main(["--endpoints", f"{ps_addr},{serve_addr}",
                        "--raw"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert {doc[ps_addr]["kind"], doc[serve_addr]["kind"]} \
            == {"ps", "serve"}
        assert doc[ps_addr]["alerts"]["active"][0]["rule"] == "fleet_rule"
    finally:
        serve.close()
        ps.close()


def test_ps_server_arms_wall_clock_history(tmp_path, monkeypatch):
    """A PS chief may have NO train boundary or scheduler round — the
    server constructor must arm the history so the wall-clock thread
    becomes its sampling beat (else worker_stalled never evaluates in the
    very process booking the last-seen gauges)."""
    monkeypatch.setenv("AUTODIST_METRICS_DIR", str(tmp_path / "hist"))
    monkeypatch.setenv("AUTODIST_METRICS_INTERVAL_S", "0.1")
    history.set_history(None)          # reset the env-arming cache
    from autodist_tpu.parallel.ps_transport import PSServer
    server = PSServer(_StubPSRunner(), host="127.0.0.1", watchdog=False)
    try:
        h = history.get_history()
        assert h is not None           # armed by the constructor
        deadline = time.monotonic() + 5.0
        while not h.samples() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.samples(), "wall-clock beat produced no sample in 5s"
        assert h.samples()[0]["reason"] == "timer"
        assert h.shards()              # and the series reached disk
    finally:
        server.close()


def test_adfleet_survives_dead_endpoint(capsys, monkeypatch):
    ps, serve = _two_servers()
    serve_addr = "%s:%d" % serve.address
    try:
        fl = _adfleet()
        # One live + one dead endpoint: renders, exits 0 (partial fleet).
        assert fl.main([serve_addr, "127.0.0.1:1", "--once"]) == 0
        out = capsys.readouterr().out
        assert "DOWN" in out and "serve" in out
        # Every endpoint dead: exit 1 (scripts gate on it).
        assert fl.main(["127.0.0.1:1", "--once"]) == 1
        capsys.readouterr()
        # No endpoints at all (and no env fallback): usage error, exit 2.
        monkeypatch.delenv("AUTODIST_PS_ADDR", raising=False)
        monkeypatch.delenv("AUTODIST_SERVE_ADDR", raising=False)
        assert fl.main(["--once"]) == 2
    finally:
        serve.close()
        ps.close()


# ----------------------------------------------------------- flag registry

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_METRICS_DIR", "AUTODIST_METRICS_PORT",
                 "AUTODIST_METRICS_INTERVAL_S", "AUTODIST_ALERT_RULES",
                 "AUTODIST_ALERT_ACTION"):
        assert flag in const.KNOWN_FLAGS
        assert hasattr(const.ENV, flag)
    assert const.ENV.AUTODIST_METRICS_DIR.val == ""
    assert const.ENV.AUTODIST_METRICS_PORT.val == ""
    assert const.ENV.AUTODIST_METRICS_INTERVAL_S.val == 0.0
    assert const.ENV.AUTODIST_ALERT_ACTION.val == "warn"
    monkeypatch.setenv("AUTODIST_METRICS_INTERVAL_S", "2.5")
    assert const.ENV.AUTODIST_METRICS_INTERVAL_S.val == 2.5
    monkeypatch.setenv("AUTODIST_ALERT_ACTION", "halt")
    eng = alerts.AlertEngine(rules=[])
    assert eng.action == "halt"
