"""Fused matmul+logsumexp kernels vs the XLA reference, values and gradients.

Same testing pattern as the flash-attention kernels: interpret mode on the
CPU-sim backend runs the identical kernel code the chip runs compiled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autodist_tpu.ops.fused_xent import fused_softmax_xent, matmul_logsumexp


def _ref_lse(h, w, b):
    logits = h.astype(jnp.float32) @ w.astype(jnp.float32)
    if b is not None:
        logits = logits + b
    return jax.nn.logsumexp(logits, axis=-1)


def _f32_tol(rtol=1e-5, atol=1e-5):
    """f32 comparison tolerance: exact-ish on CPU (the given values); on
    TPU-class backends both the kernel and the XLA reference run f32 matmuls
    at MXU (bf16-pass) precision, so two correct implementations legitimately
    differ by ~1e-3. The backend membership test lives here exactly once."""
    if jax.default_backend() in ("tpu", "axon"):
        return dict(rtol=5e-3, atol=5e-3)
    return dict(rtol=rtol, atol=atol)


def _data(n, d, v, dtype, seed=0):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(n, d), dtype) * 0.5
    w = jnp.asarray(rng.randn(d, v), dtype) * 0.1
    b = jnp.asarray(rng.randn(v), jnp.float32) * 0.1
    return h, w, b


@pytest.mark.parametrize("n,d,v", [(256, 128, 512), (200, 128, 384), (64, 64, 129)])
def test_lse_matches_reference(n, d, v):
    h, w, b = _data(n, d, v, jnp.float32)
    got = matmul_logsumexp(h, w, b, 128, 256)
    np.testing.assert_allclose(got, _ref_lse(h, w, b), rtol=1e-5, atol=1e-5)


def test_lse_no_bias():
    h, w, _ = _data(128, 64, 320, jnp.float32)
    got = matmul_logsumexp(h, w, None, 64, 128)
    np.testing.assert_allclose(got, _ref_lse(h, w, None), rtol=1e-5, atol=1e-5)


def test_grads_match_reference_f32():
    h, w, b = _data(192, 64, 300, jnp.float32, seed=3)

    def fused(h, w, b):
        return jnp.sum(matmul_logsumexp(h, w, b, 64, 128) * 0.01)

    def ref(h, w, b):
        return jnp.sum(_ref_lse(h, w, b) * 0.01)

    gf = jax.grad(fused, argnums=(0, 1, 2))(h, w, b)
    gr = jax.grad(ref, argnums=(0, 1, 2))(h, w, b)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5)


def test_fit_blocks_shrinks_for_large_d_f32_table():
    """The default (bn=512, bv=1024) tiles fit d=512 but overflow VMEM at
    d=768 with an f32 table — the dw kernel double-buffers both the table
    tile and the dw output tile, plus an f32 accumulator. The fitter must
    shrink bv at d=768 (the Mosaic backend dies mid-compile on overflow
    instead of failing cleanly) and leave the d=512 flagship tiling alone."""
    from autodist_tpu.ops.fused_xent import _VMEM_BUDGET, _fit_blocks

    N = 98304  # flagship: 384 * 256 tokens
    # bf16 h (2 bytes), f32 table (4 bytes) — the model zoo's param_dtype.
    assert _fit_blocks(512, N, 512, 1024, 2, 4, backward=True) == (512, 1024)
    bn, bv = _fit_blocks(768, N, 512, 1024, 2, 4, backward=True)
    assert bv < 1024
    dw_need = (2 * bn * 768 * 2) + (4 * 768 * bv * 4) + (4 * 768 * bv)
    assert dw_need <= _VMEM_BUDGET
    # d=1024 shrinks further but never below one lane tile.
    bn2, bv2 = _fit_blocks(1024, N, 512, 1024, 2, 4, backward=True)
    assert 128 <= bv2 <= bv
    # The backward budget covers BOTH its kernels: the dh footprint at large d
    # with f32 activations must also bound the result.
    bn3, bv3 = _fit_blocks(2048, N, 512, 1024, 4, 4, backward=True)
    dh_need = (2 * bn3 * 2048 * 4) * 2 + (2 * 2048 * bv3 * 4) + 4 * bn3 * 2048
    assert dh_need <= _VMEM_BUDGET
    # Odd lane multiples clamp at one lane tile, never below (192 -> 128,
    # not 96).
    bn4, bv4 = _fit_blocks(2048, N, 512, 192, 4, 4, backward=True)
    assert bv4 == 128 and bn4 >= 128
    # A dim no tiling can fit refuses with an actionable error instead of
    # letting the Mosaic backend die mid-compile.
    with pytest.raises(ValueError, match="VMEM"):
        _fit_blocks(32768, N, 512, 1024, 4, 4, backward=True)


def test_shrunken_blocks_stay_value_exact(monkeypatch):
    """Force the fitter to shrink at small shapes (tiny budget) and check the
    kernel still matches the XLA reference — block size must only change
    tiling, never values."""
    from autodist_tpu.ops import fused_xent as fx

    # 384 KiB: big enough for the minimum tiling (whose accounted footprint
    # now includes the dw kernel's db_acc scratch + db output tile), small
    # enough that the requested (64, 256) blocks must shrink to (64, 128).
    monkeypatch.setattr(fx, "_VMEM_BUDGET", 384 << 10)
    h, w, b = _data(128, 64, 320, jnp.float32, seed=6)
    got = fx.matmul_logsumexp(h, w, b, 64, 256)
    np.testing.assert_allclose(got, _ref_lse(h, w, b), **_f32_tol())
    gf = jax.grad(lambda h, w, b: jnp.sum(
        fx.matmul_logsumexp(h, w, b, 64, 256) * 0.01), argnums=(0, 1, 2))(h, w, b)
    gr = jax.grad(lambda h, w, b: jnp.sum(
        _ref_lse(h, w, b) * 0.01), argnums=(0, 1, 2))(h, w, b)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(a, e, **_f32_tol(rtol=2e-4, atol=2e-5))


def test_grads_bf16_track_f32():
    h, w, b = _data(128, 64, 256, jnp.bfloat16, seed=4)

    def fused(h, w, b):
        return jnp.mean(matmul_logsumexp(h, w, b, 64, 128))

    gf = jax.grad(fused, argnums=(0, 1))(h, w, b)
    gr = jax.grad(
        lambda h, w, b: jnp.mean(_ref_lse(h, w, b)), argnums=(0, 1))(
            h.astype(jnp.float32), w.astype(jnp.float32), b)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32), e,
                                   rtol=0.05, atol=0.02)


def test_fused_xent_matches_composed_loss():
    n, d, v = 160, 64, 257
    h, w, b = _data(n, d, v, jnp.float32, seed=5)
    rng = np.random.RandomState(6)
    targets = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)

    nll = fused_softmax_xent(h, w, targets, b, 64, 128)
    logits = h @ w + b
    expected = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                                    targets[:, None], axis=-1)[:, 0]
    np.testing.assert_allclose(nll, expected, **_f32_tol())

    # Full loss gradient (both the lse and the gathered true-logit paths).
    gf = jax.grad(lambda h, w: jnp.mean(fused_softmax_xent(h, w, targets, b,
                                                           64, 128)),
                  argnums=(0, 1))(h, w)
    gr = jax.grad(
        lambda h, w: jnp.mean(-jnp.take_along_axis(
            jax.nn.log_softmax(h @ w + b, axis=-1),
            targets[:, None], axis=-1)[:, 0]), argnums=(0, 1))(h, w)
    tol = _f32_tol(rtol=2e-4, atol=2e-5)
    for a, e in zip(gf, gr):
        np.testing.assert_allclose(a, e, **tol)


def test_vd_layout_matches_dv():
    """[V, D]-stored tables (reference softmax_w layout) give identical values
    and gradients without the caller transposing."""
    h, w, b = _data(192, 64, 300, jnp.float32, seed=8)
    w_vd = w.T  # stored [V, D]

    def f_dv(h, w, b):
        return jnp.sum(matmul_logsumexp(h, w, b, 64, 128) * 0.01)

    def f_vd(h, w_vd, b):
        return jnp.sum(matmul_logsumexp(h, w_vd, b, 64, 128, None, "vd") * 0.01)

    np.testing.assert_allclose(f_vd(h, w_vd, b), f_dv(h, w, b), rtol=1e-6)
    g_dv = jax.grad(f_dv, argnums=(0, 1, 2))(h, w, b)
    g_vd = jax.grad(f_vd, argnums=(0, 1, 2))(h, w_vd, b)
    np.testing.assert_allclose(g_vd[0], g_dv[0], rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(g_vd[1], g_dv[1].T, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(g_vd[2], g_dv[2], rtol=2e-4, atol=2e-5)
    # Mixed dtype: f32 table with bf16 activations, cast per-tile in the kernel.
    got = matmul_logsumexp(h.astype(jnp.bfloat16), w_vd, b, 64, 128, None, "vd")
    np.testing.assert_allclose(got, _ref_lse(h, w, b), rtol=0.02, atol=0.02)


def test_large_bias_with_padding_rows_stays_finite():
    """Regression: pad rows' lse must pad large-positive, or a bias entry > ~88
    overflows exp in the pad rows and NaNs the whole dw/db."""
    h, w, b = _data(100, 64, 256, jnp.float32, seed=9)   # 28 pad rows at bn=128
    b = b.at[5].set(95.0)
    grads = jax.grad(lambda h, w, b: jnp.mean(matmul_logsumexp(h, w, b, 128, 128)),
                     argnums=(0, 1, 2))(h, w, b)
    for g_ in grads:
        assert np.isfinite(np.asarray(g_)).all()
    gr = jax.grad(lambda h, w, b: jnp.mean(_ref_lse(h, w, b)),
                  argnums=(0, 1, 2))(h, w, b)
    for a, e in zip(grads, gr):
        np.testing.assert_allclose(a, e, rtol=2e-4, atol=2e-5)


def test_fused_xent_vd_layout_matches():
    n, d, v = 96, 64, 200
    h, w, b = _data(n, d, v, jnp.float32, seed=10)
    rng = np.random.RandomState(11)
    targets = jnp.asarray(rng.randint(0, v, (n,)), jnp.int32)
    a = fused_softmax_xent(h, w, targets, b, 64, 128)
    bb = fused_softmax_xent(h, w.T, targets, b, 64, 128, w_layout="vd")
    np.testing.assert_allclose(bb, a, rtol=1e-5, atol=1e-5)


def test_jit_and_value_under_jit():
    h, w, b = _data(128, 64, 256, jnp.float32, seed=7)
    f = jax.jit(lambda h, w, b: matmul_logsumexp(h, w, b, 64, 128))
    np.testing.assert_allclose(f(h, w, b), _ref_lse(h, w, b), rtol=1e-5, atol=1e-5)
