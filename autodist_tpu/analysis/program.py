"""Whole-program call graph: import-resolving, bounded, deterministic.

:mod:`autodist_tpu.analysis.callgraph` deliberately stops at the module
boundary — which was graftlint's documented blind spot: a ``with lock:`` body
that reaches ``runner.run`` or a socket send *through another module* passed
lint, and the last PRs' review logs show exactly that class of bug (leaked
producer threads at example call sites, a retry replaying a non-idempotent
op defined two modules away). :class:`ProgramIndex` lifts resolution to the
whole linted file set:

- **module naming** — every linted file gets a dotted module name derived
  from its repo-relative path (``autodist_tpu/data/prefetch.py`` ->
  ``autodist_tpu.data.prefetch``; ``pkg/__init__.py`` -> ``pkg``), so import
  statements can be resolved against the linted set itself. Files outside
  the set simply do not resolve — the graph is closed over what was linted.
- **import resolution** — ``import a.b [as c]``, ``from a.b import f [as g]``
  and relative ``from . import x`` forms map local names to (module, symbol)
  pairs; ``module.f()`` attribute chains resolve by longest-module-prefix.
- **instance typing** — ``x = Ctor(...)`` (local) and ``self._x = Ctor(...)``
  (instance attribute, harvested per class) bind names to classes when the
  constructor statically resolves, so ``x.m()`` / ``self._x.m()`` reach the
  method body — including across modules.
- **bounded reaching-call search** — :meth:`ProgramIndex.find_reaching_call`
  is the cross-module version of ``callgraph.find_reaching_call``:
  BFS through resolvable calls, cycle-safe, depth-limited
  (:data:`MAX_DEPTH` hops), walking only *executed* code
  (``callgraph.walk_executed`` — deferred callbacks stay deferred).

Everything here is a static over-approximation in the safe direction for
lint: unresolvable calls (dynamic dispatch, higher-order) terminate the
search rather than guessing. Resolution order is source order, so results
are deterministic for a given file set.
"""

import ast
from typing import Callable, Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph

MAX_DEPTH = 8   # cross-module hop bound for reaching-call searches


def module_dotted_name(relpath: str) -> str:
    """Dotted module name for a repo-relative ``.py`` path.
    ``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` -> ``a.b``. Leading
    ``..`` components (a path linted from OUTSIDE the root — the CLI run
    against a fixture tree) are dropped; :class:`ProgramIndex` additionally
    registers suffix aliases for those so their intra-tree imports still
    resolve."""
    rel = relpath.replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    elif rel == "__init__":
        rel = ""
    parts = [p for p in rel.split("/") if p not in ("..", ".", "")]
    return ".".join(parts)


class ModuleInfo:
    """Per-module resolution facts: defs, classes, and the import table."""

    def __init__(self, module):
        self.module = module                      # core.Module
        self.relpath: str = module.relpath
        self.dotted = module_dotted_name(module.relpath)
        tree = module.tree
        self.index = callgraph.ModuleIndex(tree) if tree is not None \
            else callgraph.ModuleIndex(ast.parse(""))
        self.classes: Dict[str, ast.ClassDef] = {}
        # local alias -> dotted module name ("import a.b as c")
        self.import_mod: Dict[str, str] = {}
        # local name -> (dotted module, symbol) ("from a.b import f as g")
        self.import_sym: Dict[str, Tuple[str, str]] = {}
        if tree is None:
            return
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
        package = self.dotted.rsplit(".", 1)[0] if "." in self.dotted else ""
        if self.relpath.endswith("__init__.py"):
            package = self.dotted
        # Walk the WHOLE tree: this codebase uses function-level imports
        # (lazy jax / tool imports) routinely, and they bind names that the
        # checks' call sites use.
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.import_mod[local] = target
                    if alias.asname is None and "." in alias.name:
                        # "import a.b.c" binds "a"; remember the full chain
                        # too so "a.b.c.f" resolves by prefix.
                        self.import_mod.setdefault(alias.name, alias.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # Relative import: climb from this module's package.
                    parts = package.split(".") if package else []
                    climb = node.level - 1
                    if climb and climb <= len(parts):
                        parts = parts[:-climb]
                    elif climb:
                        parts = []
                    base = ".".join(parts + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    self.import_sym[local] = (base, alias.name)


class Resolved:
    """One resolved callable: its module, def node, and owning class name."""

    __slots__ = ("info", "fn", "cls")

    def __init__(self, info: ModuleInfo, fn, cls: Optional[str]):
        self.info = info
        self.fn = fn
        self.cls = cls


class ProgramIndex:
    """Cross-module call resolution over a set of parsed modules."""

    def __init__(self, modules: Dict[str, object]):
        """``modules``: relpath -> ``core.Module`` (parse errors excluded)."""
        self.infos: Dict[str, ModuleInfo] = {
            rel: ModuleInfo(mod) for rel, mod in sorted(modules.items())
            if mod.tree is not None}
        self.by_dotted: Dict[str, ModuleInfo] = {}
        for rel in sorted(self.infos):
            info = self.infos[rel]
            if info.dotted:
                self.by_dotted.setdefault(info.dotted, info)
        # Out-of-tree modules (relpath escaping the root — the CLI linting
        # a fixture dir) also register their dotted-name SUFFIXES, so
        # `from pkg.sender import push` in /tmp/fixture/pkg resolves even
        # though the full dotted name is prefixed with the escape path.
        # In-root modules never get suffix aliases: the repo gate's
        # resolution stays exact. setdefault over sorted paths keeps
        # collisions deterministic (first path wins).
        for rel in sorted(self.infos):
            info = self.infos[rel]
            if rel.startswith("..") and info.dotted:
                parts = info.dotted.split(".")
                for i in range(1, len(parts)):
                    self.by_dotted.setdefault(".".join(parts[i:]), info)
        self._local_types_cache: Dict[int, Dict[str, Tuple[ModuleInfo, str]]] = {}
        self._attr_types_cache: Dict[Tuple[str, str],
                                     Dict[str, Tuple[ModuleInfo, str]]] = {}

    # ------------------------------------------------------------ module maps
    def modules(self) -> List[ModuleInfo]:
        return [self.infos[k] for k in sorted(self.infos)]

    def info_for(self, relpath: str) -> Optional[ModuleInfo]:
        return self.infos.get(relpath)

    def _split_module_prefix(self, dotted: str) \
            -> Optional[Tuple[ModuleInfo, List[str]]]:
        """Longest known-module prefix of ``dotted`` + the remainder parts."""
        parts = dotted.split(".")
        for cut in range(len(parts), 0, -1):
            info = self.by_dotted.get(".".join(parts[:cut]))
            if info is not None:
                return info, parts[cut:]
        return None

    # ------------------------------------------------------- class resolution
    def _follow_reexport(self, info: ModuleInfo, symbol: str, hops: int = 3) \
            -> Optional[Tuple[ModuleInfo, str]]:
        """Chase ``from .x import Sym`` re-export chains (package
        ``__init__.py`` surfaces) to the module that DEFINES ``symbol``."""
        while hops > 0:
            if symbol in info.classes \
                    or symbol in info.index.module_funcs:
                return info, symbol
            sym = info.import_sym.get(symbol)
            if sym is None:
                return None
            target = self.by_dotted.get(sym[0])
            if target is None:
                return None
            info, symbol = target, sym[1]
            hops -= 1
        return None

    def resolve_class(self, info: ModuleInfo, name: str) \
            -> Optional[Tuple[ModuleInfo, ast.ClassDef]]:
        """The ClassDef a (possibly dotted) name refers to from ``info``
        (following package re-export chains)."""
        if "." not in name:
            hit = self._follow_reexport(info, name)
            if hit is not None and hit[1] in hit[0].classes:
                return hit[0], hit[0].classes[hit[1]]
            return None
        head, _, rest = name.partition(".")
        base = info.import_mod.get(head)
        if base is None:
            sym = info.import_sym.get(head)
            if sym is not None:
                base = f"{sym[0]}.{sym[1]}" if sym[0] else sym[1]
        if base is None:
            return None
        hit = self._split_module_prefix(f"{base}.{rest}")
        if hit is None:
            return None
        target, remainder = hit
        if len(remainder) == 1:
            deep = self._follow_reexport(target, remainder[0])
            if deep is not None and deep[1] in deep[0].classes:
                return deep[0], deep[0].classes[deep[1]]
        return None

    def class_method(self, info: ModuleInfo, cls_name: str, method: str) \
            -> Optional[Resolved]:
        hit = self.resolve_class(info, cls_name) if "." in cls_name \
            else ((info, info.classes[cls_name]) if cls_name in info.classes
                  else self.resolve_class(info, cls_name))
        if hit is None:
            return None
        owner, cls = hit
        fn = owner.index.methods.get((cls.name, method))
        return Resolved(owner, fn, cls.name) if fn is not None else None

    # ---------------------------------------------------- instance-type facts
    def local_types(self, info: ModuleInfo, scope_node) \
            -> Dict[str, Tuple[ModuleInfo, str]]:
        """``name -> (owner module, class name)`` for ``x = Ctor(...)``
        assignments executed in ``scope_node``'s own flow."""
        cached = self._local_types_cache.get(id(scope_node))
        if cached is not None:
            return cached
        types: Dict[str, Tuple[ModuleInfo, str]] = {}
        body = getattr(scope_node, "body", None) or []
        for stmt in body:
            for node in callgraph.walk_executed(stmt):
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call):
                    continue
                ctor = callgraph.dotted_name(node.value.func)
                if ctor is None:
                    continue
                hit = self.resolve_class(info, ctor)
                if hit is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        types[target.id] = (hit[0], hit[1].name)
        self._local_types_cache[id(scope_node)] = types
        return types

    def attr_types(self, info: ModuleInfo, cls_name: str) \
            -> Dict[str, Tuple[ModuleInfo, str]]:
        """``attr -> (owner module, class name)`` for ``self.attr = Ctor()``
        assignments anywhere in class ``cls_name``'s methods."""
        key = (info.relpath, cls_name)
        cached = self._attr_types_cache.get(key)
        if cached is not None:
            return cached
        types: Dict[str, Tuple[ModuleInfo, str]] = {}
        cls = info.classes.get(cls_name)
        if cls is not None:
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call):
                    continue
                ctor = callgraph.dotted_name(node.value.func)
                if ctor is None:
                    continue
                hit = self.resolve_class(info, ctor)
                if hit is None:
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id == "self":
                        types[target.attr] = (hit[0], hit[1].name)
        self._attr_types_cache[key] = types
        return types

    # -------------------------------------------------------- call resolution
    def resolve_call(self, info: ModuleInfo, call: ast.Call,
                     current_class: Optional[str],
                     local_types: Optional[Dict] = None) -> Optional[Resolved]:
        """The def a call statically lands in, across modules, or None."""
        func = call.func
        local_types = local_types or {}
        if isinstance(func, ast.Name):
            name = func.id
            fn = info.index.module_funcs.get(name)
            if fn is not None:
                return Resolved(info, fn, None)
            hit = self.resolve_class(info, name)
            if hit is not None:    # constructor: __init__ executes in place
                owner, cls = hit
                init = owner.index.methods.get((cls.name, "__init__"))
                if init is not None:
                    return Resolved(owner, init, cls.name)
                return None
            deep = self._follow_reexport(info, name)
            if deep is not None:
                fn = deep[0].index.module_funcs.get(deep[1])
                if fn is not None:
                    return Resolved(deep[0], fn, None)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        # self.m() / cls.m()
        if isinstance(func.value, ast.Name) \
                and func.value.id in ("self", "cls") and current_class:
            fn = info.index.methods.get((current_class, func.attr))
            if fn is not None:
                return Resolved(info, fn, current_class)
            return None
        # self._attr.m() — instance-attribute typing
        if isinstance(func.value, ast.Attribute) \
                and isinstance(func.value.value, ast.Name) \
                and func.value.value.id == "self" and current_class:
            typed = self.attr_types(info, current_class).get(func.value.attr)
            if typed is not None:
                owner, cls_name = typed
                fn = owner.index.methods.get((cls_name, func.attr))
                if fn is not None:
                    return Resolved(owner, fn, cls_name)
            return None
        # obj.m() on a locally-constructed instance
        if isinstance(func.value, ast.Name):
            typed = local_types.get(func.value.id)
            if typed is not None:
                owner, cls_name = typed
                fn = owner.index.methods.get((cls_name, func.attr))
                if fn is not None:
                    return Resolved(owner, fn, cls_name)
        # module.f() / pkg.mod.f() / pkg.mod.Cls(...) attribute chains
        dotted = callgraph.dotted_name(func)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        base = info.import_mod.get(head)
        if base is None:
            sym = info.import_sym.get(head)
            if sym is not None and sym[0]:
                base = f"{sym[0]}.{sym[1]}"
        if base is None or not rest:
            return None
        hit = self._split_module_prefix(f"{base}.{rest}")
        if hit is None:
            return None
        target, remainder = hit
        if len(remainder) == 1:
            deep = self._follow_reexport(target, remainder[0])
            if deep is not None:
                target, symbol = deep
                fn = target.index.module_funcs.get(symbol)
                if fn is not None:
                    return Resolved(target, fn, None)
                cls = target.classes.get(symbol)
                if cls is not None:
                    init = target.index.methods.get((cls.name, "__init__"))
                    if init is not None:
                        return Resolved(target, init, cls.name)
        elif len(remainder) == 2:
            fn = target.index.methods.get((remainder[0], remainder[1]))
            if fn is not None:
                return Resolved(target, fn, remainder[0])
        return None

    # ------------------------------------------------- reaching-call search
    def find_reaching_call(
            self, info: ModuleInfo, start_nodes: List[ast.AST],
            current_class: Optional[str], scope_node,
            predicate: Callable[[ast.Call, ModuleInfo], Optional[str]],
            max_depth: int = MAX_DEPTH) \
            -> Optional[Tuple[ast.Call, str, List[str]]]:
        """Cross-module BFS from ``start_nodes`` for the first call where
        ``predicate(call, module_info)`` returns a label. Returns
        ``(top_level_call, label, hop_path)`` — ``hop_path`` names each
        module-qualified hop for the finding message. Depth- and
        cycle-bounded; deterministic (source order)."""
        local = self.local_types(info, scope_node) \
            if scope_node is not None else {}
        for top in start_nodes:
            for call in callgraph.calls_executed(top):
                hit = self._search(info, call, current_class, local,
                                   predicate, max_depth, visited={})
                if hit is not None:
                    label, path = hit
                    return call, label, path
        return None

    def _search(self, info: ModuleInfo, call: ast.Call,
                current_class: Optional[str], local_types: Dict,
                predicate, depth: int,
                visited: Dict[Tuple[str, int], int]):
        label = predicate(call, info)
        name = callgraph.dotted_name(call.func) or "<dynamic>"
        if label is not None:
            return label, [name]
        if depth <= 0:
            return None
        resolved = self.resolve_call(info, call, current_class, local_types)
        if resolved is None:
            return None
        key = (resolved.info.relpath, id(resolved.fn))
        # Depth-aware cycle guard: a callee first reached near the depth
        # limit was only SHALLOWLY explored — re-reaching it with more
        # budget must re-explore, or a blocking call a few hops inside it
        # goes unseen depending on statement order. Skip only when the
        # previous visit had at least this much depth left.
        if visited.get(key, -1) >= depth:
            return None
        visited[key] = depth
        callee_local = self.local_types(resolved.info, resolved.fn)
        hop = name if resolved.info is info \
            else f"{resolved.info.dotted or resolved.info.relpath}.{resolved.fn.name}"
        for stmt in resolved.fn.body:
            for inner in callgraph.calls_executed(stmt):
                hit = self._search(resolved.info, inner, resolved.cls,
                                   callee_local, predicate, depth - 1,
                                   visited)
                if hit is not None:
                    inner_label, path = hit
                    return inner_label, [hop] + path
        return None
