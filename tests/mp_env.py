"""Shared env recipe for launching single-process reference runs.

One definition of the scrub-role-env + CPU-sim-mesh + repo-on-PYTHONPATH
launch environment, used by ``strategy_matrix_mp_script.run_single_reference``
and ``seq_parallel_mp_script.run_single_reference`` — the two must stay
identical or the single-process references silently diverge from the
multi-process runs they are compared against.
"""

import os


def single_reference_env(workdir: str, device_count: int) -> dict:
    """Environment for a single-process reference subprocess: role env scrubbed
    (including a stale SYS_RESOURCE_PATH from a developer shell), CPU platform
    with ``device_count`` virtual devices, repo root prepended to PYTHONPATH,
    and ``AUTODIST_MATRIX_SINGLE=1`` so the script takes its single-process
    branch."""
    from examples.multiprocess_linear_regression import ROLE_ENV_VARS

    env = dict(os.environ)
    for k in ROLE_ENV_VARS:
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={device_count}",
        "AUTODIST_WORKING_DIR": workdir,
        "AUTODIST_MATRIX_SINGLE": "1",
        "PYTHONPATH": repo_root() + os.pathsep + env.get("PYTHONPATH", ""),
    })
    return env


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
