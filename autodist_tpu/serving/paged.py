"""Paged KV cache: block-granular decode memory + shared-prefix reuse.

The dense ``runtime.LMEngine`` allocates one ``[max_batch, max_len, H, D]``
slab row per slot, so concurrency is capped at ``max_batch`` even when every
request is short. This module replaces the slab with a POOL of fixed-size
pages (``[num_pages, page_len, H, D]`` per layer) plus a host-side page
table, vLLM-style (PAPERS.md):

- a request owns ``ceil(frontier / page_len)`` pages, allocated LAZILY as
  its write frontier crosses page boundaries and freed the moment it
  completes — admission gates on RESERVABLE PAGES (``can_admit``), not free
  slots, lifting sustainable concurrency past ``max_batch`` for short
  workloads at the SAME HBM budget;
- requests sharing a system-prompt prefix (page-aligned, matched by token
  CONTENT) reference the same immutable prefilled pages instead of
  re-prefilling them; divergence is copy-on-write at page granularity —
  writes always land in a request's OWN pages (shared columns scatter to
  the scratch page), so a cached prefix can never be corrupted by a reader.

Bit-identity with the dense engine is by construction, resting on three
empirically pinned properties of the model's decode path (f32 softmax with
an additive -1e9 mask):

1. WIDTH invariance: decode/prefill over a gathered ``K * page_len``-wide
   cache (the sub-model trick: ``dataclasses.replace(cfg, max_len=K*P)`` +
   a sliced ``pos_embed``) is bit-identical to the full-``max_len`` run —
   finite garbage beyond the masked frontier contributes exactly 0.0.
2. SPLIT-prefill exactness: prefilling a shared prefix of ``j`` pages and
   then applying only the suffix with ``cache_index = pos_offset = j*P``
   reproduces the one-shot prefill bit for bit (the prefix-cache path).
3. Decode is NOT batch-size invariant, but IS row-content independent at a
   FIXED batch — so the paged engine decodes in groups of EXACTLY
   ``max_batch`` rows (dummy rows pad short groups), one dispatch per
   group, and each row's token stream matches its dense-slab twin.

The jit cache stays bounded by the same bucketing discipline as the dense
engine (``runtime.py``): decode programs are keyed by the group's PAGE
bucket ``K`` (powers of two up to ``ceil(max_len/page_len)``), prefill
programs by ``(K, suffix_bucket)`` — admission churn never compiles.

Page REUSE without scrubbing (the dense engine's slot-reuse invariant,
restated for pages): a freed page returns to the pool with its stale K/V
intact. The next owner is safe because (a) every position a real query can
attend is either freshly written by that request's own prefill/decode or
belongs to a content-matched shared-prefix page, and (b) stale positions
beyond the frontier sit behind the additive -1e9 mask, which contributes an
exact 0.0 in the f32 softmax. ``tests/test_serve_fleet.py`` pins this by
poisoning freed pages and asserting unchanged tokens.
"""

import dataclasses
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.serving.batcher import (ServeConfig, ServeError, bucket_for,
                                          default_buckets, pad_prompt)
from autodist_tpu.telemetry import reqtrace as _reqtrace


def page_buckets(max_pages: int) -> Tuple[int, ...]:
    """Power-of-two page-count buckets up to ``max_pages`` (inclusive as the
    last bucket) — one decode program per bucket, like the prompt buckets."""
    out: List[int] = []
    b = 1
    while b < max_pages:
        out.append(b)
        b *= 2
    out.append(max_pages)
    return tuple(out)


class PageAllocator:
    """Host-side free-list + refcount + reservation ledger over the page
    pool. Page 0 is SCRATCH — never allocated; dummy decode rows and
    discarded scatter columns (shared-prefix pages, pad columns) all target
    it, so its content is garbage by design and always masked.

    Reservations make lazy frontier-crossing draws infallible: admission
    reserves a request's whole-lifetime page budget up front, so an admitted
    request can always draw its next page mid-decode — overload is decided
    once, at the admission edge, never as mid-stream corruption."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("page pool needs >= 2 pages (one is scratch)")
        self.usable = num_pages - 1
        self._free = list(range(num_pages - 1, 0, -1))   # pop() -> 1, 2, ...
        self._ref: Dict[int, int] = {}
        self._reserved = 0

    def free_count(self) -> int:
        return len(self._free)

    def available(self) -> int:
        return len(self._free) - self._reserved

    def can_reserve(self, n: int) -> bool:
        return self.available() >= n

    def reserve(self, n: int):
        if not self.can_reserve(n):
            raise ServeError(f"cannot reserve {n} KV pages "
                             f"({self.available()} available)")
        self._reserved += n

    def unreserve(self, n: int):
        self._reserved -= n
        assert self._reserved >= 0, "page reservation ledger went negative"

    def alloc(self) -> int:
        """Draw one page against an existing reservation (ref = 1)."""
        assert self._reserved > 0, "page alloc without a reservation"
        page = self._free.pop()
        self._ref[page] = 1
        self._reserved -= 1
        return page

    def retain(self, page: int):
        self._ref[page] += 1

    def release(self, page: int):
        self._ref[page] -= 1
        if self._ref[page] == 0:
            del self._ref[page]
            self._free.append(page)


class PrefixCache:
    """LRU map from page-aligned prompt-prefix BYTES to the immutable page
    chain holding its prefilled K/V. The cache owns one reference per page
    (taken by the publisher); eviction releases them — a page still shared
    with a live request survives until that request completes."""

    def __init__(self):
        self._d: "OrderedDict[bytes, List[int]]" = OrderedDict()

    def __len__(self):
        return len(self._d)

    def __contains__(self, key: bytes):
        return key in self._d

    def lookup(self, key: bytes) -> Optional[List[int]]:
        entry = self._d.get(key)
        if entry is not None:
            self._d.move_to_end(key)
        return entry

    def put(self, key: bytes, pages: List[int]):
        self._d[key] = list(pages)
        self._d.move_to_end(key)

    def pop_lru(self) -> Optional[List[int]]:
        if not self._d:
            return None
        _, pages = self._d.popitem(last=False)
        return pages


class PagedLMEngine:
    """Drop-in replacement for ``runtime.LMEngine`` with paged KV memory.

    Same engine interface the batcher drives (``capacity`` / ``admit`` /
    ``step`` / ``free`` / ``make_keys``), plus ``can_admit(prompt_len,
    max_new)`` — the page-based admission gate the batcher consults before
    assigning a slot. ``capacity`` equals USABLE PAGES (every active request
    holds at least one page), so the slot table itself never caps
    concurrency; pages do.
    """

    def __init__(self, model, params, config: Optional[ServeConfig] = None):
        config = config or ServeConfig(page_len=16)
        if config.page_len < 1:
            raise ValueError("PagedLMEngine needs page_len >= 1 "
                             "(0 selects the dense LMEngine)")
        self.model = model
        self.config = config
        self._params = params
        cfg = model.config
        self.max_len = cfg.max_len
        self.page_len = P = min(config.page_len, cfg.max_len)
        self.max_pages = (cfg.max_len + P - 1) // P        # pages per request
        # Default pool at HBM PARITY with the dense slab (max_batch rows of
        # max_len tokens) plus the scratch page — the bench gate compares
        # concurrency at equal memory.
        num_pages = config.kv_pages or (config.max_batch * self.max_pages + 1)
        self._alloc = PageAllocator(num_pages)
        self.group = config.max_batch      # decode dispatch width (fixed B)
        self.capacity = self._alloc.usable
        self.buckets = tuple(b for b in (config.buckets
                                         or default_buckets(cfg.max_len))
                             if b <= cfg.max_len)
        if not self.buckets:
            raise ValueError(f"no pad bucket fits max_len {cfg.max_len}")
        self._page_buckets = page_buckets(self.max_pages)
        self._sampling = (float(config.temperature), int(config.top_k),
                          float(config.top_p))
        self._prefix = PrefixCache() if config.prefix_cache else None
        B = self.capacity
        self._pos = np.zeros(B, np.int32)        # per-slot write frontier
        self._active = np.zeros(B, bool)
        self._last = np.zeros(B, np.int32)
        self._pages: List[List[int]] = [[] for _ in range(B)]
        self._reserved_left = np.zeros(B, np.int32)
        self._pending: List[Tuple[int, int, int]] = []   # can_admit -> admit
        self._decode_fns: Dict[int, Callable] = {}
        self._prefill_fns: Dict[Tuple[int, int], Callable] = {}
        self._submodels: Dict[int, object] = {}
        reg = telemetry.registry()
        self._m_used = reg.gauge("serve.kv.pages_used")
        self._m_free = reg.gauge("serve.kv.pages_free")
        self._m_hits = reg.counter("serve.kv.prefix_hits")
        self._m_miss = reg.counter("serve.kv.prefix_misses")
        # The pool: one dummy decode apply of the PAGE-SIZED sub-model at
        # batch num_pages creates [num_pages, P, H, D] leaves (plus the
        # scalar cache_index leaves, overridden per prefill). Content is
        # garbage — every position a real query attends is re-written first.
        pmodel = self._submodel(P)
        pp = dict(params)
        pp["pos_embed"] = np.asarray(params["pos_embed"])[:P]
        _, variables = pmodel.apply(
            {"params": pp}, jnp.zeros((num_pages, 1), jnp.int32),
            decode=True, mutable=["cache"])
        self._pool = variables["cache"]
        # Census claim on the page pool. STATIC bytes, not weakrefs: the
        # pool is a fixed-size preallocation whose leaves are replaced by
        # every prefill/decode dispatch — a weakref claim would go dead on
        # the first step, while the footprint it names never changes.
        try:
            from autodist_tpu.telemetry import memplane
            pool_bytes = sum(
                int(getattr(leaf, "nbytes", 0) or 0)
                for leaf in jax.tree_util.tree_leaves(self._pool))
            memplane.tag("kv_pages", pool_bytes, key=f"pool.{id(self)}")
        except Exception:  # noqa: BLE001 — census is best-effort
            pass
        self._set_gauges()

    # ------------------------------------------------------------- jit cache

    def _submodel(self, width: int):
        """The model re-instantiated at ``max_len=width`` — the WIDTH
        invariance trick: a gathered K-page context runs through a
        ``K*P``-wide twin whose ``pos_embed`` is sliced (or zero-padded past
        max_len; such positions are only ever pad-junk, masked + overwritten
        before any real query attends them)."""
        m = self._submodels.get(width)
        if m is None:
            m = self._submodels[width] = type(self.model)(
                dataclasses.replace(self.model.config, max_len=width))
        return m

    def _pos_embed_for(self, params, width: int):
        pe = params["pos_embed"]
        if width <= pe.shape[0]:
            return pe[:width]
        return jnp.concatenate(
            [pe, jnp.zeros((width - pe.shape[0], pe.shape[1]), pe.dtype)], 0)

    def _gather(self, pool, table, width: int, idx_fill):
        """Pool pages -> dense ``[rows, width, H, D]`` context per table
        row; scalar leaves (cache_index) are overridden with ``idx_fill``
        (the suffix write offset for prefill; unused by vector decode)."""
        def g(leaf):
            if leaf.ndim == 0:
                return jnp.full_like(leaf, idx_fill)
            rows = leaf[table]                    # [B, K, P, ...]
            return rows.reshape(rows.shape[0], width, *leaf.shape[2:])
        return jax.tree_util.tree_map(g, pool)

    def _decode(self, K: int):
        fn = self._decode_fns.get(K)
        if fn is not None:
            return fn
        P, L = self.page_len, K * self.page_len
        smodel = self._submodel(L)
        temp, top_k, top_p = self._sampling
        from autodist_tpu.models.common import sample_logits

        def decode_step(params, pool, table, toks, pos, keys):
            p2 = dict(params)
            p2["pos_embed"] = self._pos_embed_for(params, L)
            gathered = self._gather(pool, table, L, 0)
            logits, variables = smodel.apply(
                {"params": p2, "cache": gathered}, toks[:, None],
                pos_offset=pos, decode=True, mutable=["cache"])
            lg = logits[:, 0]
            if temp == 0.0:
                nxt = sample_logits(lg, None, 0.0)
            else:
                # Per-row keys, exactly the dense engine's sampling path.
                nxt = jax.vmap(lambda l, k: sample_logits(
                    l[None], k, temp, top_k, top_p)[0])(lg, keys)
            # Scatter back ONLY the frontier page per row (the vector decode
            # path writes exactly one position); dummy/pad rows target the
            # scratch page 0, where duplicate garbage writes are harmless.
            pidx = pos // P                                     # [B]
            newc = variables["cache"]

            def scat(pl, nl):
                if nl.ndim == 0:
                    return pl
                rows = nl.reshape(nl.shape[0], K, P, *nl.shape[2:])
                sel = jnp.take_along_axis(
                    rows, pidx.reshape((-1,) + (1,) * (rows.ndim - 1)),
                    axis=1)[:, 0]                               # [B, P, ...]
                tgt = jnp.take_along_axis(table, pidx[:, None], axis=1)[:, 0]
                return pl.at[tgt].set(sel)
            pool = jax.tree_util.tree_map(scat, pool, newc)
            return pool, nxt

        # The pool dominates serving HBM and every step rewrites one page
        # per row: donated, callers rebind on the same line (runtime.py's
        # shared-cache discipline, unchanged under paging).
        fn = self._decode_fns[K] = jax.jit(decode_step, donate_argnums=(1,))
        return fn

    def _prefill(self, K: int, bs: int):
        """Unified (cold + prefix-hit) prefill: gather ``K`` pages (shared
        chain + fresh own pages + scratch pads) to a dense context, apply
        ONLY the suffix chunk at ``cache_index = pos_offset = j*P`` (the
        split-prefill exactness property), project the last real position,
        scatter own columns back (shared/pad columns dump to scratch —
        that 0-redirect keeps ``j`` dynamic, so one program serves every
        prefix length within the ``(K, bs)`` bucket)."""
        fn = self._prefill_fns.get((K, bs))
        if fn is not None:
            return fn
        P, L = self.page_len, K * self.page_len
        smodel = self._submodel(L)
        temp, top_k, top_p = self._sampling
        tied = self.model.config.tied_output
        from autodist_tpu.models.common import lm_head_logits, sample_logits

        def prefill(params, pool, src, tgt, suffix, s_len, j_tok, key):
            p2 = dict(params)
            p2["pos_embed"] = self._pos_embed_for(params, L)
            gathered = self._gather(pool, src[None], L, j_tok)
            hidden, variables = smodel.apply(
                {"params": p2, "cache": gathered}, suffix,
                pos_offset=j_tok, decode=True, return_hidden=True,
                mutable=["cache"])
            last_h = jax.lax.dynamic_slice_in_dim(hidden, s_len - 1, 1,
                                                  axis=1)[:, 0]
            lg = lm_head_logits(last_h, p2, tied=tied)
            first = sample_logits(lg, key, temp, top_k, top_p)[0]
            newc = variables["cache"]

            def scat(pl, nl):
                if nl.ndim == 0:
                    return pl
                rows = nl.reshape(K, P, *nl.shape[2:])
                return pl.at[tgt].set(rows)
            pool = jax.tree_util.tree_map(scat, pool, newc)
            return pool, first

        fn = self._prefill_fns[(K, bs)] = jax.jit(prefill,
                                                  donate_argnums=(1,))
        return fn

    @staticmethod
    def _k_pow2(needed: int) -> int:
        """Prefill gather-width bucket: smallest power of two >= needed.
        Unlike decode, prefill width may exceed ``max_pages`` (suffix
        BUCKET padding can reach past the true frontier); the extra
        columns gather/scatter scratch, so rounding up is cheap."""
        k = 1
        while k < needed:
            k *= 2
        return k

    # --------------------------------------------------------- page ledger

    def _pages_total(self, plen: int, max_new: int) -> int:
        """Whole-lifetime page budget: the last position ever WRITTEN is
        ``plen + max_new - 2`` (prefill writes [0, plen); the decode steps
        producing tokens 2..max_new write plen..plen+max_new-2)."""
        assert max_new >= 1
        return (plen + max_new - 2) // self.page_len + 1

    def _evict_for(self, n: int):
        """LRU-drop prefix-cache entries until ``n`` pages are reservable
        (or the cache is empty) — cached prefixes are a perf optimization
        and must never out-prioritize admitting a live request."""
        if self._prefix is None:
            return
        while len(self._prefix) and not self._alloc.can_reserve(n):
            for page in self._prefix.pop_lru() or []:
                self._alloc.release(page)

    def can_admit(self, prompt_len: int, max_new_tokens: int,
                  rid=None) -> bool:
        """The batcher's admission gate: True RESERVES the request's whole
        page budget (consumed by the matching ``admit``, FIFO); False = not
        yet (the batcher holds the request back); a request that can NEVER
        fit raises ``ServeError`` (rejected, not head-of-line-blocked). The
        budget ignores possible prefix sharing — conservative, so a lazy
        draw can never fail; ``admit`` returns the savings. ``rid`` is the
        request's trace key; when the gate holds the request back, an
        ``admit_wait`` mark records the page shortfall against it.

        Under device memory pressure (the memory plane's ``mem.pressure``
        at/above its threshold) the gate demands ``total`` plus a holdback
        (:func:`~autodist_tpu.telemetry.memplane.kv_admission_holdback`)
        before admitting — NEW requests shed first while in-flight
        reservations keep their whole budget, so pressure degrades
        admission throughput instead of corrupting mid-decode draws."""
        total = self._pages_total(prompt_len, max_new_tokens)
        if total > self._alloc.usable:
            raise ServeError(
                f"request needs {total} KV pages but the pool owns only "
                f"{self._alloc.usable} (page_len={self.page_len})")
        holdback = 0
        try:
            from autodist_tpu.telemetry import memplane
            holdback = memplane.kv_admission_holdback(self._alloc.usable)
        except Exception:  # noqa: BLE001 — pressure probe must not gate
            holdback = 0
        if not self._alloc.can_reserve(total + holdback):
            self._evict_for(total + holdback)
        if not self._alloc.can_reserve(total + holdback):
            if rid is not None:
                _reqtrace.mark(rid, "admit_wait", pages_needed=total,
                               pages_free=self._alloc.free_count(),
                               holdback=holdback)
            return False
        self._alloc.reserve(total)
        self._pending.append((prompt_len, max_new_tokens, total))
        return True

    def _take_reservation(self, plen: int,
                          max_new_tokens: Optional[int]) -> Tuple[int, int]:
        """(budget, max_new) for this admit: the head of the can_admit FIFO
        (the batcher admits in gate order), or a fresh worst-case
        reservation for direct drivers that skipped the gate."""
        if self._pending:
            rplen, rmax_new, total = self._pending.pop(0)
            assert rplen == plen, "admit order diverged from can_admit order"
            return total, rmax_new
        max_new = max(1, max_new_tokens if max_new_tokens is not None
                      else self.max_len - plen)
        total = self._pages_total(plen, max_new)
        if not self._alloc.can_reserve(total):
            self._evict_for(total)
        self._alloc.reserve(total)       # raises ServeError when impossible
        return total, max_new

    def _set_gauges(self):
        free = self._alloc.free_count()
        self._m_used.set(self._alloc.usable - free)
        self._m_free.set(free)

    # ------------------------------------------------------ engine interface

    def make_keys(self, seed: int, n: int) -> Optional[np.ndarray]:
        """Identical key schedule to the dense engine (and to
        :func:`transformer_lm.generate`); None for greedy."""
        if self._sampling[0] == 0.0:
            return None
        return np.asarray(jax.random.split(jax.random.PRNGKey(seed), n))

    def admit(self, slot: int, prompt: np.ndarray,
              key: Optional[np.ndarray],
              max_new_tokens: Optional[int] = None) -> int:
        """Prefill ``prompt`` into ``slot``'s page chain; returns the first
        sampled token. Shared-prefix pages (matched by token content at
        page granularity) are referenced, not recomputed; only the suffix
        runs. ``max_new_tokens`` is only needed when ``can_admit`` was not
        called first (direct drivers) — the batcher's gate already carries
        the page budget through the reservation FIFO."""
        P = self.page_len
        plen = int(prompt.size)
        total, _ = self._take_reservation(plen, max_new_tokens)
        # Longest content-matched page-aligned prefix, capped at
        # (plen-1)//P pages so the suffix keeps >= 1 token (the first
        # sampled token must come from a real suffix hidden state).
        j, shared = 0, []
        if self._prefix is not None:
            for m in range((plen - 1) // P, 0, -1):
                entry = self._prefix.lookup(prompt[:m * P].tobytes())
                if entry is not None:
                    j, shared = m, entry
                    break
            (self._m_hits if j else self._m_miss).inc()
        now = (plen - 1) // P + 1 - j          # pages covering the prompt
        for page in shared:
            self._alloc.retain(page)
        self._alloc.unreserve(j)               # the conservative gate's
        own = [self._alloc.alloc() for _ in range(now)]   # prefix savings
        s_len = plen - j * P                   # >= 1 by the j cap
        bs = bucket_for(s_len, self.buckets)
        K = self._k_pow2(j + (bs + P - 1) // P)
        src = np.zeros(K, np.int32)
        tgt = np.zeros(K, np.int32)            # shared/pad columns -> scratch
        src[:j] = shared
        src[j:j + now] = own
        tgt[j:j + now] = own
        suffix = pad_prompt(prompt[j * P:], bs)
        key = jnp.zeros((2,), jnp.uint32) if key is None else key
        self._pool, first = self._prefill(K, bs)(
            self._params, self._pool, src, tgt, suffix,
            np.int32(s_len), np.int32(j * P), key)
        first = int(jax.device_get(first))
        self._pages[slot] = list(shared) + own
        self._reserved_left[slot] = total - j - now
        self._pos[slot] = plen
        self._active[slot] = True
        self._last[slot] = first
        # Publish this prompt's longest whole-page prefix (cold AND hit
        # admits — a hit may extend a shorter cached chain). Published
        # pages are never written again: the owner's decode frontier
        # starts at page >= (plen-1)//P + ... >= m_pub, and later readers
        # scatter their shared columns to scratch.
        if self._prefix is not None:
            m_pub = (plen - 1) // P
            if m_pub >= 1:
                kb = prompt[:m_pub * P].tobytes()
                if kb not in self._prefix:
                    chain = self._pages[slot][:m_pub]
                    for page in chain:
                        self._alloc.retain(page)
                    self._prefix.put(kb, chain)
        self._set_gauges()
        return first

    def step(self, keys: Optional[np.ndarray] = None) -> np.ndarray:
        """One decode step for every ACTIVE slot, dispatched in groups of
        exactly ``self.group`` rows (short groups padded with dummy rows at
        page 0 / position 0 — decode is row-content independent at fixed
        batch, so padding never changes results); returns ``[capacity]``
        sampled tokens indexed by slot."""
        P = self.page_len
        out = np.zeros(self.capacity, np.int32)
        active = np.nonzero(self._active)[0]
        if active.size == 0:
            return out
        if keys is None:
            keys = np.zeros((self.capacity, 2), np.uint32)
        # Lazy frontier-crossing draws — infallible (reserved at admission).
        for s in active:
            need = int(self._pos[s]) // P + 1
            while len(self._pages[s]) < need:
                assert self._reserved_left[s] > 0, "page budget underflow"
                self._pages[s].append(self._alloc.alloc())
                self._reserved_left[s] -= 1
        B = self.group
        for g0 in range(0, active.size, B):
            slots = active[g0:g0 + B]
            kneed = max(len(self._pages[s]) for s in slots)
            K = bucket_for(kneed, self._page_buckets)
            table = np.zeros((B, K), np.int32)
            toks = np.zeros(B, np.int32)
            pos = np.zeros(B, np.int32)
            gkeys = np.zeros((B, 2), np.uint32)
            for i, s in enumerate(slots):
                chain = self._pages[s]
                table[i, :len(chain)] = chain
                toks[i] = self._last[s]
                pos[i] = self._pos[s]
                gkeys[i] = keys[s]
            self._pool, nxt = self._decode(K)(
                self._params, self._pool, table, toks, pos, gkeys)
            nxt = np.asarray(jax.device_get(nxt))
            for i, s in enumerate(slots):
                out[s] = nxt[i]
        self._pos = np.where(self._active, self._pos + 1, 0).astype(np.int32)
        self._last = np.where(self._active, out, 0).astype(np.int32)
        self._set_gauges()
        return out

    def free(self, slot: int):
        """Release the slot's page chain (shared pages decrement their
        refcount; a page returns to the pool at ref 0 with its stale K/V
        INTACT — the page-reuse staleness invariant in the module
        docstring) and unreserve any unfulfilled lazy budget (early EOS)."""
        for page in self._pages[slot]:
            self._alloc.release(page)
        self._pages[slot] = []
        if self._reserved_left[slot]:
            self._alloc.unreserve(int(self._reserved_left[slot]))
            self._reserved_left[slot] = 0
        self._active[slot] = False
        self._pos[slot] = 0
        self._last[slot] = 0
        self._set_gauges()

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    def pool_snapshot(self) -> dict:
        """Wire-encodable pool view for status/consoles."""
        free = self._alloc.free_count()
        return {"page_len": self.page_len,
                "pages_total": self._alloc.usable,
                "pages_used": self._alloc.usable - free,
                "pages_free": free,
                "prefix_entries": len(self._prefix or ())}

    def compiled_programs(self) -> Tuple[int, int]:
        """(prefill programs, total jitted entry points) — the jit-cache
        boundedness the (K, bucket) keying exists for; tests pin it."""
        return (len(self._prefill_fns),
                len(self._prefill_fns) + len(self._decode_fns))
