"""GL011 — wire-retry idempotency: the opcode/retry contract, whole-program.

PR 14's review log: the wire retry replayed ``register(None)`` after a
transport reset — each replay ALLOCATED a fresh worker slot, leaving a
phantom live registration pinning ``min(steps)`` forever. The fix carved it
out of the retry policy (``ps_transport._retry_safe``), and the policy's
ground truth is a reified table: :data:`ps_transport.IDEMPOTENT_OPS`. But a
table nothing checks rots like any convention — this check joins it to
GL006's dispatch-arm tables, ACROSS modules:

- **every ``IDEMPOTENT_OPS`` member must have a ``_dispatch`` arm**
  somewhere in the program. A typo'd member (``"regster"``) silently
  changes retry policy for the real opcode — the request surfaces its
  first transient failure instead of retrying — and a stale member is dead
  vocabulary masquerading as a contract.
- **every opcode literal flowing into ``call_raw`` directly**
  (``client.call_raw(("op", ...), counters)`` — the overlapped/background
  exchange shape, in ANY module, found via cross-module receiver typing)
  **must be in ``IDEMPOTENT_OPS``**: ``call_raw``'s transparent
  reconnect-and-retry consults the table, so an unclassified op on that
  path gets NO retry and its mid-exchange failure poisons an overlapped
  socket with no protocol recovery — and classifying it carelessly is the
  ``register(None)`` replay. Either the op is replay-safe (add it to the
  table, with the carve-outs ``_retry_safe`` documents) or it belongs on
  ``call()``'s surface-the-error path.
- **every ``.call("op")`` on a transport client resolved across modules**
  (``adtop``'s ``_PSClient(address).call("status")``) must have a
  ``_dispatch`` arm somewhere in the program — the cross-module lift of
  GL006, which only pairs sends with arms inside one module.

The check activates only when the program defines an ``IDEMPOTENT_OPS``
set; fixture trees without the contract are out of scope.
"""

import ast
from typing import List, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, register_program
from autodist_tpu.analysis.checks.wire_protocol import _str_compares


def _idempotent_ops(program) -> List[Tuple[object, ast.Assign, Set[str]]]:
    """(module info, assignment node, member set) for every
    ``IDEMPOTENT_OPS = frozenset({...})`` / set / tuple literal — in
    NON-TEST modules (a test fake's table must not define the contract,
    the GL009 symmetry rule)."""
    out = []
    for info in program.modules():
        if info.relpath.startswith("tests/"):
            continue
        for node in info.module.tree.body:
            if not isinstance(node, ast.Assign) or not any(
                    isinstance(t, ast.Name) and t.id == "IDEMPOTENT_OPS"
                    for t in node.targets):
                continue
            value = node.value
            if isinstance(value, ast.Call) \
                    and callgraph.last_attr(value.func) in ("frozenset",
                                                            "set") \
                    and value.args:
                value = value.args[0]
            elts = getattr(value, "elts", None)
            if elts is None and isinstance(value, ast.Set):
                elts = value.elts
            if elts is None:
                continue
            members = {e.value for e in elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, str)}
            out.append((info, node, members))
    return out


def _program_dispatch_arms(program) -> Set[str]:
    """Union of every ``_dispatch`` arm table in NON-TEST modules
    (module-level functions and methods — GL006's per-module tables,
    joined). A test fake server's arms must not mask a missing production
    arm, exactly as a test-booked metric must not mask a dead selector."""
    arms: Set[str] = set()
    for info in program.modules():
        if info.relpath.startswith("tests/"):
            continue
        fns = []
        if "_dispatch" in info.index.module_funcs:
            fns.append(info.index.module_funcs["_dispatch"])
        fns.extend(fn for (cls, name), fn in info.index.methods.items()
                   if name == "_dispatch")
        for fn in fns:
            arms |= _str_compares(fn, "op")
    return arms


def _transport_client_classes(program) -> Set[Tuple[str, str]]:
    """(relpath, class name) of classes defining BOTH ``call_raw`` and
    ``call`` — the raw-exchange + checked-reply pairing that identifies a
    transport client (a class that merely happens to name some method
    ``call_raw`` is not one)."""
    out: Set[Tuple[str, str]] = set()
    for info in program.modules():
        have_raw = {cls for (cls, name) in info.index.methods
                    if name == "call_raw"}
        have_call = {cls for (cls, name) in info.index.methods
                     if name == "call"}
        for cls in have_raw & have_call:
            out.add((info.relpath, cls))
    return out


def _receiver_is_transport_client(program, info, call: ast.Call,
                                  clients: Set[Tuple[str, str]],
                                  scope_fn, current_class) -> bool:
    """Does this ``.call``/``.call_raw`` receiver statically resolve to a
    class that defines ``call_raw``? Resolution covers locally-constructed
    instances, ``self._client``-style attributes, ``self`` inside such a
    class, and ANNOTATED parameters (``client: _PSClient`` — the overlapped
    prefetch helper's shape)."""
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    recv = func.value
    if isinstance(recv, ast.Name):
        if recv.id == "self" and current_class \
                and (info.relpath, current_class) in clients:
            return True
        local = program.local_types(info, scope_fn) \
            if scope_fn is not None else {}
        typed = local.get(recv.id)
        if typed is not None:
            return (typed[0].relpath, typed[1]) in clients
        if scope_fn is not None:
            args = scope_fn.args
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if a.arg == recv.id and a.annotation is not None:
                    dotted = callgraph.dotted_name(a.annotation)
                    hit = program.resolve_class(info, dotted) \
                        if dotted else None
                    return hit is not None \
                        and (hit[0].relpath, hit[1].name) in clients
        return False
    if isinstance(recv, ast.Attribute) \
            and isinstance(recv.value, ast.Name) \
            and recv.value.id == "self" and current_class:
        typed = program.attr_types(info, current_class).get(recv.attr)
        return typed is not None \
            and (typed[0].relpath, typed[1]) in clients
    return False


def _enclosing_fn_and_class(module, index, node):
    """(innermost enclosing def, owning class name) for a call node."""
    best = callgraph.innermost_function(module.tree, node)
    cls = None
    if best is not None:
        scope = module.scope_at(node)
        head = scope.split(".")[0] if scope else ""
        if any(c == head for c, _ in index.methods):
            cls = head
    return best, cls


@register_program("GL011", "wire opcode outside the idempotency contract "
                           "or retry table without a dispatch arm",
                  full_program=True)
def check_wire_idempotency(program, ctx: Context) -> List[Finding]:
    """GL011 — wire-retry idempotency (see the module docstring).

    The contract under test is ``ps_transport``'s: ``IDEMPOTENT_OPS`` is
    the retry policy's ground truth (PR 14's ``register(None)`` replay is
    the incident class), ``_dispatch`` arm tables are the vocabulary
    (GL006), and ``call_raw`` is the raw-exchange surface background paths
    use. All three are joined program-wide, so an op sent from ``tools/``
    against an arm defined in ``parallel/`` — or a raw exchange added two
    modules away from the table — is checked the same as a same-module one.
    """
    findings: List[Finding] = []
    tables = _idempotent_ops(program)
    if not tables:
        return []
    all_ops: Set[str] = set()
    for _, _, members in tables:
        all_ops |= members
    arms = _program_dispatch_arms(program)
    clients = _transport_client_classes(program)

    # -- table members need arms somewhere ----------------------------------
    if arms:
        for info, node, members in tables:
            for op in sorted(members - arms):
                findings.append(Finding(
                    "GL011", info.relpath, node.lineno, node.col_offset,
                    f"IDEMPOTENT_OPS member {op!r} has no `_dispatch` arm "
                    f"anywhere in the program; a typo'd or stale entry "
                    f"silently changes the retry policy for the real "
                    f"opcode",
                    scope=info.module.scope_at(node)))

    # -- raw-exchange ops must be classified; client sends need arms --------
    for info in program.modules():
        module = info.module
        if module.relpath.startswith("tests/"):
            continue   # tests deliberately send bogus ops at error paths
        for call in callgraph.calls_under(module.tree):
            last = callgraph.last_attr(call.func)
            if last == "call_raw" and isinstance(call.func, ast.Attribute) \
                    and call.args and isinstance(call.args[0], ast.Tuple) \
                    and call.args[0].elts \
                    and isinstance(call.args[0].elts[0], ast.Constant) \
                    and isinstance(call.args[0].elts[0].value, str):
                op = call.args[0].elts[0].value
                scope_fn, cls = _enclosing_fn_and_class(module, info.index,
                                                        call)
                if not _receiver_is_transport_client(
                        program, info, call, clients, scope_fn, cls):
                    continue   # some unrelated class's call_raw method
                if op not in all_ops:
                    findings.append(Finding(
                        "GL011", module.relpath, call.lineno,
                        call.col_offset,
                        f"opcode {op!r} flows into the raw retry path "
                        f"(`call_raw`) but is not in IDEMPOTENT_OPS; an "
                        f"unclassified op gets no reconnect-retry and its "
                        f"mid-exchange failure poisons the overlapped "
                        f"socket — classify it (only if a replay is safe: "
                        f"the register(None) lesson) or route it through "
                        f"`call()`",
                        scope=module.scope_at(call)))
                continue
            if last != "call" or not isinstance(call.func, ast.Attribute) \
                    or not call.args \
                    or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                continue
            op = call.args[0].value
            if op in arms or not arms:
                continue
            scope_fn, cls = _enclosing_fn_and_class(module, info.index, call)
            if not _receiver_is_transport_client(program, info, call,
                                                 clients, scope_fn, cls):
                continue
            findings.append(Finding(
                "GL011", module.relpath, call.lineno, call.col_offset,
                f"opcode {op!r} is sent on a transport client but no "
                f"`_dispatch` in the whole program has an arm for it; "
                f"every request would error as unknown-op (GL006, lifted "
                f"across modules)",
                scope=module.scope_at(call)))
    return findings
