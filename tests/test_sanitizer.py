"""graftsan runtime sanitizer (testing/sanitizer.py).

Every armed scenario is deterministic: the ABBA schedule is event-gated so
the reverse acquisition always happens AFTER the forward edge is recorded
(and raises instead of deadlocking), the leak fence gets a thread parked on
an event the test controls, and every assertion on ``violations()`` runs
INSIDE the ``armed(...)`` context — exiting it resets the sanitizer's state
for test isolation.
"""

import json
import threading

import pytest

from autodist_tpu.testing import sanitizer as san
from autodist_tpu.testing.sanitizer import (SanViolation, san_condition,
                                            san_event, san_lock, san_rlock)


# ------------------------------------------------------------ disarmed = bare

def test_disarmed_factories_return_bare_primitives():
    with san.armed(""):
        assert type(san_lock()) is type(threading.Lock())          # noqa: E721
        assert type(san_rlock()) is type(threading.RLock())        # noqa: E721
        assert isinstance(san_condition(), threading.Condition)
        assert isinstance(san_event(), threading.Event)


def test_disarmed_condition_unwraps_sanitized_lock():
    with san.armed("locks"):
        wrapped = san_lock("outer")
    with san.armed(""):
        cond = san_condition(wrapped)
        assert isinstance(cond, threading.Condition)
        with cond:   # usable: the REAL lock was extracted from the wrapper
            cond.notify_all()


# ----------------------------------------------------------------- lock order

def test_dynamic_abba_aborts_with_both_stacks():
    with san.armed("locks"):
        a, b = san_lock("lockA"), san_lock("lockB")
        forward_done = threading.Event()
        caught = []

        def forward():
            with a:
                with b:        # records the a -> b edge
                    pass
            forward_done.set()

        def reverse():
            forward_done.wait(5.0)
            try:
                with b:
                    with a:    # b -> a closes the cycle: must raise, not hang
                        pass
            except SanViolation as e:
                caught.append(str(e))

        t1 = threading.Thread(target=forward, name="abba-forward")
        t2 = threading.Thread(target=reverse, name="abba-reverse")
        t1.start(), t2.start()
        t1.join(5.0), t2.join(5.0)
        assert not t1.is_alive() and not t2.is_alive()

        assert caught, "reverse acquisition was not aborted"
        msg = caught[0]
        assert "lock-order cycle" in msg
        assert "lockA" in msg and "lockB" in msg
        # BOTH sides of the inversion carry full stacks: the aborting
        # thread's held+acquiring frames AND the recorded forward thread's.
        assert "this thread" in msg and "other thread" in msg
        assert "abba-forward" in msg          # the recorded edge names its thread
        assert msg.count('File "') >= 4       # 2 stacks per side
        vs = san.violations()
        assert [v["kind"] for v in vs] == ["locks"]


def test_recursive_plain_lock_acquire_is_a_self_deadlock():
    with san.armed("locks"):
        lk = san_lock("plain")
        lk.acquire()
        try:
            with pytest.raises(SanViolation, match="self-deadlock"):
                lk.acquire()
            # try-acquire cannot deadlock: reported as a plain failure,
            # and the optimistic hold count is undone (release still works)
            assert lk.acquire(blocking=False) is False
        finally:
            lk.release()
        assert not lk.locked()


def test_rlock_reentrancy_is_not_a_violation():
    with san.armed("locks"):
        rl = san_rlock("re")
        with rl:
            with rl:
                assert rl.locked()
        assert san.violations() == []


def test_same_site_siblings_do_not_self_edge():
    # Lock arrays share one creation-site key; acquiring two SIBLINGS nested
    # must not record a self-edge (which would be an instant "cycle").
    with san.armed("locks"):
        shards = [san_lock("shard") for _ in range(2)]
        with shards[0]:
            with shards[1]:
                pass
        assert san.observed_edges() == []
        assert san.violations() == []


# ---------------------------------------------------------------------- waits

def test_untimed_condition_wait_flagged():
    with san.armed("locks,waits"):
        cond = san_condition(name="cv")
        with cond:
            with pytest.raises(SanViolation, match="without a timeout"):
                cond.wait()
        vs = san.violations()
        assert vs and vs[0]["kind"] == "waits"


def test_timed_wait_while_holding_another_lock_flagged():
    with san.armed("locks,waits"):
        lk = san_lock("held")
        ev = san_event("gate")
        with lk:
            with pytest.raises(SanViolation, match="while holding"):
                ev.wait(0.01)


def test_clean_timed_wait_passes():
    with san.armed("locks,waits"):
        cond = san_condition(name="ok")
        with cond:
            cond.wait(0.01)      # timed, no other lock held: clean
        ev = san_event("ok_ev")
        ev.set()
        assert ev.wait(0.01) is True
        assert san.violations() == []


# --------------------------------------------------------------- thread fence

def test_thread_fence_fires_on_leaked_nondaemon_thread():
    release = threading.Event()
    leaker = threading.Thread(target=lambda: release.wait(10.0),
                              name="fence-leaker")
    try:
        with san.armed("threads"):
            with pytest.raises(SanViolation) as exc:
                with san.thread_fence(grace_s=0.1):
                    leaker.start()
            assert "fence-leaker" in str(exc.value)
            assert "leaked 1 non-daemon thread" in str(exc.value)
    finally:
        release.set()
        leaker.join(5.0)


def test_thread_fence_passes_when_threads_join():
    with san.armed("threads"):
        with san.thread_fence(grace_s=1.0):
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join(5.0)


# --------------------------------------------------------------------- export

def test_observed_edges_export_and_dump(tmp_path):
    with san.armed("locks"):
        a, b = san_lock("expA"), san_lock("expB")
        with a:
            with b:
                pass
        edges = san.observed_edges()
        assert any(e["outer"]["name"] == "expA"
                   and e["inner"]["name"] == "expB"
                   and e["count"] == 1 for e in edges)
        assert all(e["outer"]["path"] for e in edges)

        out = san.dump_observed(str(tmp_path / "obs.jsonl"))
        lines = [json.loads(line) for line in open(out, encoding="utf-8")]
        # meta header first (artifact is non-empty even edge-free), then edges
        assert "meta" in lines[0]
        assert lines[0]["meta"]["edges"] == len(edges)
        assert any("outer" in rec for rec in lines[1:])


def test_dump_observed_writes_meta_for_edge_free_run(tmp_path):
    with san.armed("locks"):
        out = san.dump_observed(str(tmp_path / "empty.jsonl"))
        lines = [json.loads(line) for line in open(out, encoding="utf-8")]
        assert lines and "meta" in lines[0]
        assert lines[0]["meta"]["edges"] == 0
