"""Fused LM-head softmax cross-entropy — pallas TPU kernels.

The separable-head formulation of the LM loss is

    nll_n = lse_n - true_logit_n,   lse_n = logsumexp_v(h_n . w_v + b_v)

where the [N, V] logits tensor (4.2 GB at the flagship's N=65k, V=32k, bf16) is
pure intermediate: XLA materializes it out of the head matmul, reads it for the
log-softmax reductions, and reads/writes it again for d(logits) in the backward
— the single largest HBM consumer in the training step. These kernels compute
``lse`` (and its VJP) **without ever materializing logits in HBM**: each
[n-block, v-block] logits tile lives only in VMEM, reduced on the fly with the
same online-logsumexp state machine as the flash-attention kernel
(``ops/flash_attention.py``), and the backward recomputes tiles from the saved
``lse`` exactly like flash attention recomputes scores (FlashAttention-2 style).
The true-logit term is a cheap gather-einsum left to XLA.

``w`` is accepted in either layout — ``[D, V]`` (flax Dense kernel) or
``[V, H]`` (the reference's softmax_w; ``w_layout="vd"``) — and is cast to the
activation dtype **per tile inside the kernel**, so no transposed or downcast
copy of a multi-GiB table is ever materialized, and its gradient comes back in
the stored layout/dtype directly.

Three kernels:
- forward: grid (n-blocks, v-blocks); VMEM scratch carries (m, l) across the v
  dimension; last v-block writes ``lse = m + log l``.
- d(h):    grid (n-blocks, v-blocks); accumulates g*p @ w^T tiles in VMEM.
- d(w,b):  grid (v-blocks, n-blocks); accumulates h^T @ g*p and column-sums.

Measured on a v5e chip: in the full flagship training step the fused head is
faster than the XLA head at equal batch (410k vs 398k tokens/s at bs 256) and
— because nothing here scales with N*V — unlocks batch sizes whose logits
cannot exist: bs 384 (~428k tokens/s, the flagship bench config) OOMs with a
materialized head. Larger still: V=262k (32 GiB of logits) and N=262k
(16 GiB) both train where XLA OOMs, and the lm1b example trains its exact
793,471-word vocabulary with the TRUE softmax objective (48 GiB of logits if
materialized; the reference needed sampled softmax) at ~17k words/s/chip end
to end (bs 96, Adafactor — Adam's unfactored moments on the 4.9 GiB of
tables exceed one chip's HBM).
(An isolated loss+grads microbench is near-parity — 73 vs 69 ms —
because the two backward logit recomputes cost roughly what the avoided HBM
traffic saves; inside the full step, overlap with the rest of the model tips
it to a win.)

On non-TPU backends the kernels run in pallas interpret mode, so the CPU-sim
test mesh exercises the same code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.ops.blockwise_attention import NEG_INF
from autodist_tpu.ops.flash_attention import _use_interpret

_LANES = 128
DEFAULT_N_BLOCK = 512
DEFAULT_V_BLOCK = 1024
# Padding rows' lse: large POSITIVE so exp(logits - lse) underflows to exactly 0
# whatever the bias — padding with 0 would overflow exp for bias values > ~88
# and poison dw/db with NaN through inf * 0.
_PAD_LSE = 1e30


def _logits_tile(h_ref, w_ref, b_ref, w_vd: bool, vi, bv: int, v: int):
    """([bn, bv] f32 logits tile, cast+masked w tile). The single place the
    per-tile activation-dtype cast happens — w is contracted per its stored
    layout with no HBM copy of the table. The arrays are NOT padded to block
    multiples (padding would copy the multi-GiB table every step): the ragged
    last vocab tile reads undefined memory, which is zero-masked on the w side
    (so no garbage inf/NaN can ride a contraction) and -inf-masked in the
    logits (so the softmax never sees the lanes)."""
    wt = w_ref[...].astype(h_ref.dtype)
    col = vi * bv + jax.lax.broadcasted_iota(jnp.int32, wt.shape,
                                             0 if w_vd else 1)
    wt = jnp.where(col < v, wt, jnp.zeros((), wt.dtype))
    dims = (((1,), (1,)), ((), ())) if w_vd else (((1,), (0,)), ((), ()))
    logits = jax.lax.dot_general(h_ref[...], wt, dims,
                                 preferred_element_type=jnp.float32)
    logits = logits + b_ref[0][None, :]
    lane = vi * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    return jnp.where(lane < v, logits, NEG_INF), wt


# ------------------------------------------------------------------- forward

def _fwd_kernel(h_ref, w_ref, b_ref, lse_ref, m_ref, l_ref, *, n_v: int,
                w_vd: bool, bv: int, v: int):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    logits, _ = _logits_tile(h_ref, w_ref, b_ref, w_vd, vi, bv, v)  # [bn, bv]
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
    p = jnp.exp(logits - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_prev * jnp.exp(m_prev - m_new) + p.sum(axis=-1, keepdims=True),
        l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(vi == n_v - 1)
    def _finish():
        lse_ref[0, ni, :] = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))


def _shapes(h, w, bn, bv, w_vd: bool):
    n, d = h.shape
    v = w.shape[0] if w_vd else w.shape[1]
    return n, d, v, pl.cdiv(n, bn), pl.cdiv(v, bv)


# Per-core VMEM the kernels may plan against (v5e has 16 MiB; ~1 MiB headroom
# for the compiler's own buffers — the estimates below match Mosaic's measured
# scoped allocations within ~0.2 MiB). Exceeding the physical limit does not
# fail cleanly — the Mosaic backend can die mid-compile — so block sizes are
# fitted up front.
_VMEM_BUDGET = 15 << 20


def _fit_blocks(d: int, n: int, bn: int, bv: int, h_size: int, w_size: int,
                backward: bool):
    """Shrink (bn, bv) until every kernel launched with them fits the budget.

    The footprint scales with BOTH the model dim and the table dtype — a
    [d, bv] float32 table tile is double-buffered on input AND (for the dw
    kernel) on output, plus an f32 accumulator — so the defaults that fit
    d=512 overflow at d=768 with an f32 table. The backward pass launches TWO
    kernels (dh and dw/db) with the same blocks, so it budgets against the
    max of both footprints, plus the fully-resident [n_n, bn] lse/g planes
    (whole-array BlockSpecs, ~4 bytes per padded row each). Halving clamps at
    one lane tile; block size only changes tiling, not results (beyond fp
    summation order).

    Vocab blocks shrink first: halving bv keeps the total table traffic and
    the row-block count (hence table passes) unchanged, while halving bn
    doubles the fwd/dh kernels' full-table re-streams — measured 15% slower
    on the 793k-vocab full-softmax when bn gives way first."""
    def need(bn_, bv_):
        n_pad = -(-n // bn_) * bn_
        planes = (2 if backward else 1) * 4 * n_pad  # lse (+ g) resident f32
        h_tiles = 2 * bn_ * d * h_size
        w_tiles = 2 * d * bv_ * w_size
        # fwd/dh shape: + output [bn, d] tile + f32 [bn, d] accumulator (the
        # fwd kernel's (bn, LANES) scratch is strictly smaller: conservative).
        row_kernel = h_tiles + w_tiles + 2 * bn_ * d * h_size + 4 * bn_ * d
        if not backward:
            return row_kernel + planes
        # dw output tile (double-buffered) + f32 dw accumulator + the
        # [_LANES, bv] f32 db accumulator scratch + double-buffered (1, bv)
        # db output tile — 512 KiB+ at the default bv, enough to push a
        # just-under-budget fit over physical VMEM.
        dw_kernel = (h_tiles + w_tiles + 2 * d * bv_ * w_size + 4 * d * bv_
                     + 4 * _LANES * bv_ + 2 * bv_ * w_size)
        return max(row_kernel, dw_kernel) + planes
    while bv > _LANES and need(bn, bv) > _VMEM_BUDGET:
        bv = max(_LANES, bv // 2)
    while bn > _LANES and need(bn, bv) > _VMEM_BUDGET:
        bn = max(_LANES, bn // 2)
    if need(bn, bv) > _VMEM_BUDGET:
        # Refusing beats proceeding: over budget, the Mosaic backend can die
        # mid-compile with an unactionable tunnel error instead of raising.
        raise ValueError(
            f"fused_softmax_xent: even the minimum ({bn}, {bv}) tiling "
            f"needs {need(bn, bv) / 2**20:.1f} MiB of VMEM (budget "
            f"{_VMEM_BUDGET / 2**20:.0f} MiB) at d={d} with a "
            f"{w_size}-byte table dtype; use a smaller model dim, a bf16 "
            f"table, or the XLA head (fused_head=False)")
    return bn, bv


def _w_spec(d, bv, w_vd, index2):
    """BlockSpec for one vocab tile of w in its stored layout. ``index2`` maps
    grid coords to the vocab-block index."""
    if w_vd:
        return pl.BlockSpec((bv, d), lambda *a: (index2(*a), 0))
    return pl.BlockSpec((d, bv), lambda *a: (0, index2(*a)))


def _forward(h, w, b, bn, bv, interpret, w_vd):
    bn, bv = _fit_blocks(h.shape[1], h.shape[0], bn, bv, h.dtype.itemsize,
                         w.dtype.itemsize, backward=False)
    n, d, v, n_n, n_v = _shapes(h, w, bn, bv, w_vd)
    lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n_v=n_v, w_vd=w_vd, bv=bv, v=v),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            _w_spec(d, bv, w_vd, lambda i, j: j),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
        ],
        # Whole [n_n, bn] plane resident (a [1, bn] block violates TPU tiling);
        # 4 bytes/row — same layout rationale as the flash kernel's lse.
        out_specs=pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, n_n, bn), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(h, w, b.reshape(1, -1))
    return lse.reshape(n_n * bn)[:n]


# ------------------------------------------------------------------ backward

def _dh_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dh_ref, acc_ref, *, n_v: int,
               w_vd: bool, bv: int, v: int):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits, wt = _logits_tile(h_ref, w_ref, b_ref, w_vd, vi, bv, v)
    lse = lse_ref[0, ni, :]                                   # [bn]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]  # [bn, bv]
    dims = (((1,), (0,)), ((), ())) if w_vd else (((1,), (1,)), ((), ()))
    acc_ref[:] += jax.lax.dot_general(
        gp.astype(wt.dtype), wt, dims,
        preferred_element_type=jnp.float32)                   # [bn, d]

    @pl.when(vi == n_v - 1)
    def _finish():
        dh_ref[...] = acc_ref[:].astype(dh_ref.dtype)


def _dwdb_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dw_ref, db_ref,
                 dw_acc, db_acc, *, n_n: int, w_vd: bool, bn: int, bv: int,
                 n: int, v: int):
    vi = pl.program_id(0)
    ni = pl.program_id(1)  # read at top level: program_id is invalid inside when-bodies in interpret mode

    @pl.when(ni == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    logits, _ = _logits_tile(h_ref, w_ref, b_ref, w_vd, vi, bv, v)  # [bn, bv]
    lse = lse_ref[0, ni, :]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]
    # The dw/db contraction runs over the row (token) axis, so the ragged last
    # row block's undefined lanes must be hard zeros on BOTH operands: gp rows
    # (g pads to 0, but 0 * garbage-inf logits would be NaN) and h rows.
    row = ni * bn + jax.lax.broadcasted_iota(jnp.int32, gp.shape, 0)
    gp = jnp.where(row < n, gp, 0.0)
    hrow = ni * bn + jax.lax.broadcasted_iota(jnp.int32, h_ref.shape, 0)
    ht = jnp.where(hrow < n, h_ref[...], jnp.zeros((), h_ref.dtype))
    gph = gp.astype(ht.dtype)
    if w_vd:
        dw_acc[:] += jax.lax.dot_general(                     # [bv, d]
            gph, ht, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        dw_acc[:] += jax.lax.dot_general(                     # [d, bv]
            ht, gph, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    db_acc[:, :] += jnp.broadcast_to(gp.sum(axis=0)[None, :], db_acc.shape)

    @pl.when(ni == n_n - 1)
    def _finish():
        dw_ref[...] = dw_acc[:].astype(dw_ref.dtype)
        db_ref[...] = db_acc[:1, :].astype(db_ref.dtype)


def _backward(h, w, b, lse, g, bn, bv, interpret, w_vd):
    bn, bv = _fit_blocks(h.shape[1], h.shape[0], bn, bv, h.dtype.itemsize,
                         w.dtype.itemsize, backward=True)
    n, d, v, n_n, n_v = _shapes(h, w, bn, bv, w_vd)
    bvec = b.reshape(1, -1)
    # The lse/g planes are tiny [N] vectors; padding THEM is cheap (unlike the
    # table). Padding rows must contribute nothing: gradient pads as zero AND
    # lse pads large-positive so exp underflows (see _PAD_LSE).
    lse_p = jnp.pad(lse, (0, n_n * bn - n),
                    constant_values=_PAD_LSE).reshape(1, n_n, bn)
    g_p = jnp.pad(g.astype(jnp.float32), (0, n_n * bn - n)).reshape(1, n_n, bn)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, n_v=n_v, w_vd=w_vd, bv=bv, v=v),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            _w_spec(d, bv, w_vd, lambda i, j: j),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(h, w, bvec, lse_p, g_p)

    dw_shape = (v, d) if w_vd else (d, v)
    dw_scratch = pltpu.VMEM((bv, d) if w_vd else (d, bv), jnp.float32)
    dw, db = pl.pallas_call(
        functools.partial(_dwdb_kernel, n_n=n_n, w_vd=w_vd, bn=bn, bv=bv,
                          n=n, v=v),
        grid=(n_v, n_n),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            _w_spec(d, bv, w_vd, lambda j, i: j),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
        ],
        out_specs=(
            _w_spec(d, bv, w_vd, lambda j, i: j),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct(dw_shape, w.dtype),
            jax.ShapeDtypeStruct((1, v), jnp.float32),
        ),
        scratch_shapes=[
            dw_scratch,
            pltpu.VMEM((_LANES, bv), jnp.float32),
        ],
        interpret=interpret,
    )(h, w, bvec, lse_p, g_p)
    return dh, dw, db[0]


# ----------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def matmul_logsumexp(h, w, b, n_block: int = DEFAULT_N_BLOCK,
                     v_block: int = DEFAULT_V_BLOCK,
                     interpret: bool = None, w_layout: str = "dv"):
    """``logsumexp(h @ w + b, axis=-1)`` without materializing the logits.

    h: [N, D] (bf16/f32); w: [D, V] (``w_layout="dv"``, flax Dense kernel) or
    [V, D] (``w_layout="vd"``, reference softmax_w layout); b: [V] or None.
    Returns f32 [N]. Differentiable in h, w, b (custom VJP recomputes logits
    tiles from the saved lse); dw returns in w's stored layout and dtype.
    """
    lse, _ = _mls_fwd(h, w, b, n_block, v_block, interpret, w_layout)
    return lse


def _w_vd(w_layout: str) -> bool:
    if w_layout not in ("dv", "vd"):
        raise ValueError(f"w_layout must be 'dv' or 'vd', got {w_layout!r}")
    return w_layout == "vd"


def _mls_fwd(h, w, b, n_block, v_block, interpret, w_layout):
    if interpret is None:
        interpret = _use_interpret()
    w_vd = _w_vd(w_layout)
    has_bias = b is not None
    v = w.shape[0] if w_vd else w.shape[1]
    bvec = b if has_bias else jnp.zeros((v,), jnp.float32)
    lse = _forward(h, w, bvec, n_block, v_block, interpret, w_vd)
    return lse, (h, w, bvec, lse, has_bias)


def _mls_bwd(n_block, v_block, interpret, w_layout, res, g):
    if interpret is None:
        interpret = _use_interpret()
    h, w, bvec, lse, has_bias = res
    dh, dw, db = _backward(h, w, bvec, lse, g, n_block, v_block, interpret,
                           _w_vd(w_layout))
    return dh, dw, (db if has_bias else None)


matmul_logsumexp.defvjp(_mls_fwd, _mls_bwd)


def fused_softmax_xent(h, w, targets, b=None, n_block: int = DEFAULT_N_BLOCK,
                       v_block: int = DEFAULT_V_BLOCK,
                       w_layout: str = "dv") -> jax.Array:
    """Per-row NLL of ``targets`` under ``softmax(h @ w + b)`` — the fused-head
    loss. h: [N, D], w per ``w_layout``, targets: int [N]. Returns f32 [N].

    The lse term runs through the pallas kernels; the true-logit term is a
    gather-einsum XLA handles well (its grad is the row-sparse scatter).
    """
    lse = matmul_logsumexp(h, w, b, n_block, v_block, None, w_layout)
    if _w_vd(w_layout):
        w_true = jnp.take(w, targets, axis=0).astype(h.dtype)   # [N, D]
        true_logit = jnp.einsum("nd,nd->n", h, w_true,
                                preferred_element_type=jnp.float32)
    else:
        w_true = jnp.take(w, targets, axis=1).astype(h.dtype)   # [D, N]
        true_logit = jnp.einsum("nd,dn->n", h, w_true,
                                preferred_element_type=jnp.float32)
    if b is not None:
        true_logit = true_logit + b[targets]
    return lse - true_logit
