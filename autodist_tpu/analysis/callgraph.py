"""Small intra-module AST call-graph utilities shared by the checks.

Scope is deliberately one module: graftlint's concurrency checks need to see
through local helpers (``_send_msg -> _send_payload -> sock.sendmsg``), not
across the whole import graph. Resolution covers the two shapes this codebase
uses: bare-name calls to module-level functions, and ``self.x()`` calls to
methods of the enclosing class.
"""

import ast
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple


def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain; None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_attr(node) -> Optional[str]:
    """The final component of a Name/Attribute chain (``c`` of ``a.b.c``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def name_tokens(name: Optional[str]) -> Set[str]:
    """Lower-cased underscore tokens of an identifier (``_write_mutex`` ->
    {"write", "mutex"}). Token matching avoids substring traps ("block"
    contains "lock")."""
    if not name:
        return set()
    return {t for t in name.lower().split("_") if t}


class ModuleIndex:
    """Per-module map of callable definitions for bounded call resolution."""

    def __init__(self, tree: ast.Module):
        self.module_funcs: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.func_class: Dict[int, Optional[str]] = {}  # id(def) -> class name
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
                self.func_class[id(node)] = None
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[(node.name, item.name)] = item
                        self.func_class[id(item)] = node.name

    def resolve(self, call: ast.Call,
                current_class: Optional[str]) -> Optional[ast.FunctionDef]:
        """The local FunctionDef a call lands in, when statically knowable."""
        func = call.func
        if isinstance(func, ast.Name):
            return self.module_funcs.get(func.id)
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id in ("self", "cls") and current_class:
            return self.methods.get((current_class, func.attr))
        return None


def calls_under(node) -> Iterator[ast.Call]:
    """Every Call node in ``node``'s subtree, in source order."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def walk_executed(node) -> Iterator[ast.AST]:
    """``ast.walk`` that does NOT descend into function/lambda bodies:
    code inside a ``def``/``lambda`` under a ``with lock:`` is *deferred* —
    it runs when the callback is called, not while the lock is held — so
    lock-holding analyses must skip it (the nested def gets analyzed in its
    own right by module-wide walks). Decorators and argument defaults DO
    execute in place and are walked. Applies to the start node too: to walk
    a function's own body, iterate its ``.body`` statements."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(n.decorator_list)
            stack.extend(n.args.defaults)
            stack.extend(d for d in n.args.kw_defaults if d is not None)
            continue
        if isinstance(n, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(n))


def calls_executed(node) -> Iterator[ast.Call]:
    """Call nodes that actually execute as part of ``node``'s own flow
    (see :func:`walk_executed`)."""
    for sub in walk_executed(node):
        if isinstance(sub, ast.Call):
            yield sub


def find_reaching_call(
        index: ModuleIndex, start_nodes: List[ast.AST],
        current_class: Optional[str],
        predicate: Callable[[ast.Call], Optional[str]],
        max_depth: int = 5) -> Optional[Tuple[ast.Call, str, List[str]]]:
    """BFS from ``start_nodes`` through locally-resolvable calls for the first
    call where ``predicate`` returns a non-None label.

    Returns ``(top_level_call, label, path)`` where ``top_level_call`` is the
    call *in the start nodes* that leads there and ``path`` names the hop
    chain (for the finding message). Depth-limited and cycle-safe."""
    for top in start_nodes:
        for call in calls_executed(top):
            hit = _search(index, call, current_class, predicate,
                          max_depth, visited=set())
            if hit is not None:
                label, path = hit
                return call, label, path
    return None


def _search(index: ModuleIndex, call: ast.Call,
            current_class: Optional[str], predicate, depth: int,
            visited: Set[int]) -> Optional[Tuple[str, List[str]]]:
    label = predicate(call)
    name = dotted_name(call.func) or "<dynamic>"
    if label is not None:
        return label, [name]
    if depth <= 0:
        return None
    target = index.resolve(call, current_class)
    if target is None or id(target) in visited:
        return None
    visited.add(id(target))
    callee_class = index.func_class.get(id(target), current_class)
    for stmt in target.body:
        for inner in calls_executed(stmt):
            hit = _search(index, inner, callee_class, predicate, depth - 1,
                          visited)
            if hit is not None:
                label, path = hit
                return label, [name] + path
    return None
