"""Unified runtime telemetry: host-side span tracing, a process-global
metrics registry, and the exporters feeding the cross-worker stats plane.

Three planes, one subsystem (docs/usage/observability.md):

- **Spans** (:mod:`autodist_tpu.telemetry.spans`) — ``telemetry.span("name")``
  context manager / ``@telemetry.traced()`` decorator recording a host
  timeline into a bounded ring buffer; ``export_chrome_trace(path)`` writes
  Perfetto-loadable Chrome trace-event JSON.
- **Metrics** (:mod:`autodist_tpu.telemetry.metrics`) — named
  Counter/Gauge/Histogram instruments with a deterministic, wire-encodable
  ``snapshot()``; ``emit_metrics()`` rides the benchmark-logger JSONL sink.
- **Stats plane** — the PS transport's ``stats`` opcode ships a remote
  process's snapshot to whoever asks
  (:meth:`autodist_tpu.parallel.ps_transport.RemotePSWorker.stats`).
- **Cluster trace plane** (:mod:`autodist_tpu.telemetry.cluster`) — span
  rings cross the PS wire (``trace``/``push_trace`` opcodes, ``ping``-based
  clock-offset estimation) and :func:`collect_cluster_trace` merges them
  into ONE clock-aligned Chrome trace with a ``pid`` lane per worker;
  ``tools/tracedump.py`` does the same offline from JSONL ring dumps.

- **Training-health plane** (:mod:`autodist_tpu.telemetry.health`) —
  ``AUTODIST_HEALTH=1`` adds a fused on-device numerics bundle (grad norm,
  update/param ratio, NaN/Inf count) to the existing jitted step plus a
  host-side loss-spike monitor at log boundaries; anomalies become
  ``health.anomaly`` events and the ``AUTODIST_HEALTH_ACTION`` policy
  (warn / record / halt / recover — the last rolls back to the newest
  last-known-good snapshot and resumes, ``parallel/recovery.py``) decides
  the reaction.
- **Flight recorder** (:mod:`autodist_tpu.telemetry.recorder`) — anomaly
  events (watchdog, health, the manual ``record`` wire opcode) capture
  self-contained snapshot dirs (merged cluster trace + metrics/events +
  env manifest) into a bounded latest-K ring; ``tools/adtop.py`` is the
  live console over the ``status`` opcode.
- **Performance attribution** (:mod:`autodist_tpu.telemetry.profiling` +
  :mod:`autodist_tpu.telemetry.costmodel`) — ``AUTODIST_PROFILE=1`` caches
  XLA cost analysis per compiled program signature, decomposes each log
  period into ``train.attr.*`` phase shares, books ``train.mfu`` /
  ``train.membw_util`` roofline gauges, and writes a schema-versioned
  per-run profile (``AUTODIST_PROFILE_DIR``); ``tools/adprof.py`` diffs
  two profiles and the cost model predicts step time from static costs
  plus a calibration fitted from one run.

- **Fleet metrics plane** (:mod:`autodist_tpu.telemetry.history` /
  :mod:`openmetrics` / :mod:`alerts`) — ``AUTODIST_METRICS_DIR`` retains a
  timestamped registry series (in-memory ring + rotation-capped JSONL
  shards), ``AUTODIST_METRICS_PORT`` serves Prometheus-format ``/metrics``
  + ``/healthz`` from any trainer chief / PSServer / InferenceServer
  process, and ``AUTODIST_ALERT_RULES`` evaluates declarative
  threshold/burn-rate/drift rules on every sample (firing books
  ``alert.active.*`` gauges, emits ``alert`` events, triggers the flight
  recorder, and honors ``AUTODIST_ALERT_ACTION``); ``tools/adfleet.py``
  merges ``status`` across N endpoints into one fleet screen.

- **Memory plane** (:mod:`autodist_tpu.telemetry.memplane`) — an
  owner-attributed HBM census (``mem.owned.*`` from weakref claims the
  train loop / paged-KV engine / prefetch producers register), a budget
  with a booked source (measured / env / warned default), the
  ``mem.pressure`` ratio the shipped ``mem_pressure`` alert rule
  thresholds, tuner memory pre-flight (``pruned: oom`` before any compile
  probe), and OOM forensics (a ``memory`` section in every flight-recorder
  manifest: census + per-program ledger + predicted-vs-live peak).

Everything is OFF by default; ``AUTODIST_TELEMETRY=1`` (or
:func:`telemetry.enable`) turns recording on. Disabled-mode instrumentation
costs one attribute check per span (gated in ``bench.py
--telemetry-overhead``); disabled health monitors cost one attribute check
per train step (``bench.py --health-overhead`` gates the enabled side).
"""

from autodist_tpu.telemetry import (alerts, history, memplane, openmetrics,
                                    reqtrace)
from autodist_tpu.telemetry.alerts import (AlertEngine, AlertHalt,
                                           AlertRecover, AlertRule)
from autodist_tpu.telemetry.cluster import (collect_cluster_trace,
                                            dump_events_jsonl,
                                            dump_reqtrace_jsonl,
                                            dump_spans_jsonl,
                                            load_events_jsonl,
                                            load_reqtrace_jsonl,
                                            load_trace_jsonl,
                                            local_reqtrace_state,
                                            local_trace_state,
                                            merge_trace_states, ntp_offset,
                                            reqtrace_marks)
from autodist_tpu.telemetry.export import (chrome_trace_events, emit_metrics,
                                           export_chrome_trace,
                                           opt_state_bytes,
                                           sample_device_memory)
from autodist_tpu.telemetry.health import (HealthConfig, HealthHalt,
                                           HealthMonitor, HealthRecover)
from autodist_tpu.telemetry.history import MetricsHistory
from autodist_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                            Registry, counter, event, events,
                                            gauge, histogram, merge_histograms,
                                            quantile, registry, snapshot)
from autodist_tpu.telemetry.openmetrics import MetricsExporter
from autodist_tpu.telemetry import costmodel, profiling
from autodist_tpu.telemetry.profiling import (peak_spec, profile_document,
                                              write_profile)
from autodist_tpu.telemetry.recorder import (FlightRecorder, build_manifest,
                                             get_recorder, maybe_record,
                                             set_recorder)
from autodist_tpu.telemetry.spans import (clear, disable, enable, enabled,
                                          snapshot_spans, span, traced)

__all__ = [
    "span", "traced", "enable", "disable", "enabled", "clear",
    "snapshot_spans",
    "Counter", "Gauge", "Histogram", "Registry",
    "counter", "gauge", "histogram", "registry", "snapshot",
    "event", "events",
    "export_chrome_trace", "chrome_trace_events", "emit_metrics",
    "sample_device_memory", "opt_state_bytes",
    "collect_cluster_trace", "local_trace_state", "merge_trace_states",
    "dump_spans_jsonl", "load_trace_jsonl", "ntp_offset",
    "dump_events_jsonl", "load_events_jsonl",
    "reqtrace", "local_reqtrace_state", "reqtrace_marks",
    "dump_reqtrace_jsonl", "load_reqtrace_jsonl",
    "HealthConfig", "HealthHalt", "HealthMonitor", "HealthRecover",
    "FlightRecorder", "set_recorder", "get_recorder", "maybe_record",
    "build_manifest",
    "profiling", "costmodel", "peak_spec", "profile_document",
    "write_profile",
    "alerts", "history", "memplane", "openmetrics",
    "AlertEngine", "AlertHalt", "AlertRecover", "AlertRule",
    "MetricsHistory",
    "MetricsExporter", "quantile", "merge_histograms",
]
