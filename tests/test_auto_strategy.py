"""AutoStrategy: the analytic cost model picks the right regime per parameter.

The reference has no auto builder (its default is a fixed PSLoadBalancing,
``autodist.py:70``; auto-learning is named as future work in its tutorials), so
these tests pin this builder's own decision contract: regime by memory budget,
sparse->PS, large->partitioned, codec by node count/bandwidth — and that the
emitted strategy trains value-exactly like the fixed builder it reduces to.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce, AutoStrategy
from shardmap_compat import requires_shard_map

AR = strategy_pb2.AllReduceSynchronizer


def _spec(yaml_text=None):
    return ResourceSpec(yaml_text) if yaml_text else ResourceSpec(
        "nodes: [{address: localhost, tpus: 8, chief: true}]")


def _dense_params(n=3, dim=16):
    rng = np.random.RandomState(0)
    return {f"w{i}": rng.randn(dim, dim).astype(np.float32) for i in range(n)}


def _which(node):
    return node.WhichOneof("synchronizer")


def test_small_dense_model_goes_allreduce():
    strategy = AutoStrategy().build(ModelSpec(_dense_params()), _spec())
    kinds = {n.var_name: _which(n) for n in strategy.proto.node_config}
    assert set(kinds.values()) == {"all_reduce_synchronizer"}
    axes = {a.name: a.size for a in strategy.proto.mesh_config.axes}
    assert axes.get("data") == 8
    assert axes.get("reduce", 1) == 1


def test_memory_bound_model_goes_ps():
    # 3 x 1 MiB params with a 1 MiB budget -> PS/ZeRO regime.
    params = {f"w{i}": np.zeros((512, 512), np.float32) for i in range(3)}
    strategy = AutoStrategy(memory_budget_bytes=1 << 20).build(
        ModelSpec(params), _spec())
    kinds = {_which(n) for n in strategy.proto.node_config}
    assert kinds == {"ps_synchronizer"}
    axes = {a.name: a.size for a in strategy.proto.mesh_config.axes}
    assert axes.get("reduce") == 8  # ZeRO sharding across all devices


def test_sparse_param_goes_ps_dense_goes_ar():
    params = {"emb": np.zeros((100, 8), np.float32),
              "w": np.zeros((8, 8), np.float32)}
    strategy = AutoStrategy().build(
        ModelSpec(params, sparse_names=["emb"]), _spec())
    kinds = {n.var_name: _which(n) for n in strategy.proto.node_config}
    assert kinds["emb"] == "ps_synchronizer"
    assert kinds["w"] == "all_reduce_synchronizer"


def test_large_param_is_partitioned():
    params = {"big": np.zeros((4096, 4096), np.float32),   # 64 MiB
              "small": np.zeros((8, 8), np.float32)}
    builder = AutoStrategy(partition_threshold_bytes=32 << 20)
    strategy = builder.build(ModelSpec(params), _spec())
    nodes = {n.var_name: n for n in strategy.proto.node_config}
    assert max(nodes["big"].partitioner.num_shards) >= 2
    assert len(nodes["big"].part_config) >= 2
    assert not nodes["small"].partitioner.num_shards
    assert "partition threshold" in builder.explain()
    # The mesh carves a real model axis so the sharding is physical, and the
    # shard count matches it (64 MiB / 32 MiB threshold -> 2-way).
    axes = {a.name: a.size for a in strategy.proto.mesh_config.axes}
    assert axes.get("model") == 2
    assert max(nodes["big"].partitioner.num_shards) == 2


def test_multinode_low_bandwidth_picks_compressed_dcn():
    yaml_two_nodes = """
nodes:
  - {address: 10.0.0.1, tpus: 4, chief: true, network_bandwidth: 10}
  - {address: 10.0.0.2, tpus: 4, network_bandwidth: 10}
"""
    strategy = AutoStrategy().build(ModelSpec(_dense_params()), _spec(yaml_two_nodes))
    for node in strategy.proto.node_config:
        assert node.all_reduce_synchronizer.spec == AR.DCN
        assert node.all_reduce_synchronizer.compressor == AR.BF16_EF


def test_multinode_fast_link_stays_uncompressed():
    yaml_two_nodes = """
nodes:
  - {address: 10.0.0.1, tpus: 4, chief: true, network_bandwidth: 400}
  - {address: 10.0.0.2, tpus: 4, network_bandwidth: 400}
"""
    strategy = AutoStrategy().build(ModelSpec(_dense_params()), _spec(yaml_two_nodes))
    for node in strategy.proto.node_config:
        assert node.all_reduce_synchronizer.compressor == AR.NONE


def test_multinode_unspecified_bandwidth_stays_lossless():
    """No stated network_bandwidth: the defaulted 1 GBE value must NOT buy a
    numerics-changing lossy codec — hierarchical reduce yes, compression no."""
    yaml_two_nodes = """
nodes:
  - {address: 10.0.0.1, tpus: 4, chief: true}
  - {address: 10.0.0.2, tpus: 4}
"""
    builder = AutoStrategy()
    strategy = builder.build(ModelSpec(_dense_params()), _spec(yaml_two_nodes))
    for node in strategy.proto.node_config:
        assert node.all_reduce_synchronizer.spec == AR.DCN
        assert node.all_reduce_synchronizer.compressor == AR.NONE
    assert "bandwidth unspecified" in builder.explain()


def test_multinode_dcn_carves_inner_mesh_axis():
    """The DCN knob needs a populated inner DP axis: AutoStrategy's emitted
    mesh must be {reduce: chips/node, data: nodes}, not {data: all}."""
    yaml_two_nodes = """
nodes:
  - {address: 10.0.0.1, tpus: 4, chief: true, network_bandwidth: 400}
  - {address: 10.0.0.2, tpus: 4, network_bandwidth: 400}
"""
    strategy = AutoStrategy().build(ModelSpec(_dense_params()), _spec(yaml_two_nodes))
    axes = {a.name: a.size for a in strategy.proto.mesh_config.axes}
    assert axes.get("reduce") == 4   # intra-node ICI tier
    assert axes.get("data") == 2     # cross-node DCN tier


@requires_shard_map
def test_autostrategy_dcn_lowering_is_hierarchical():
    """End-to-end: the strategy AutoStrategy emits for a 2x4 multi-node spec
    actually lowers to the two-phase reduce (the knob is honored, not inert),
    and gradients stay value-exact vs the single-node AllReduce lowering."""
    from autodist_tpu.parallel import synchronization
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.plan import ShardingPlan

    yaml_two_nodes = """
nodes:
  - {address: 10.0.0.1, tpus: 4, chief: true, network_bandwidth: 400}
  - {address: 10.0.0.2, tpus: 4, network_bandwidth: 400}
"""
    rng = np.random.RandomState(2)
    params = {f"w{i}": jnp.asarray(rng.randn(8, 4), jnp.float32)
              for i in range(3)}
    batch = {"x": rng.randn(16, 8).astype(np.float32),
             "y": rng.randn(16, 4).astype(np.float32)}

    def loss(p, b):
        out = sum((i + 1.0) * (b["x"] @ p[k]) for i, k in enumerate(sorted(p)))
        return jnp.mean((b["y"] - out) ** 2)

    def lower(builder, spec):
        model = ModelSpec.from_loss_fn(loss, params, batch)
        strategy = builder.build(model, spec)
        plan = ShardingPlan.from_strategy(strategy, model)
        mesh = build_mesh(axes=dict(plan.mesh_axes))
        grad_fn = synchronization.make_grad_fn(plan, model, mesh, loss)
        ef = synchronization.init_ef_state(plan, params, mesh=mesh)
        text = jax.jit(grad_fn).lower(params, batch, ef).as_text()
        with mesh:
            grads, *_ = jax.jit(grad_fn)(params, batch, ef)
        return grads, text

    g_auto, _ = lower(AllReduce(), _spec())
    g_dcn, text = lower(AutoStrategy(), _spec(yaml_two_nodes))
    # Explicit shard_map lowering with the two reduce phases (+1 for the loss);
    # the NONE codec keeps the wire lossless.
    n_reduces = sum("stablehlo.all_reduce" in l for l in text.splitlines())
    assert n_reduces == 3, f"expected 2 hierarchical phases + loss, got {n_reduces}"
    for k in g_auto:
        np.testing.assert_allclose(np.asarray(g_dcn[k]), np.asarray(g_auto[k]),
                                   rtol=1e-5, atol=1e-6)


def test_end_to_end_matches_fixed_builder():
    """Where the model reduces to plain AllReduce, training is value-exact."""
    rng = np.random.RandomState(1)
    params = {"w": rng.randn(4, 1).astype(np.float32), "b": np.zeros((1,), np.float32)}
    batch = {"x": rng.randn(32, 4).astype(np.float32),
             "y": rng.randn(32, 1).astype(np.float32)}

    def loss_fn(p, b):
        return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)

    def run(builder):
        ad = AutoDist(strategy_builder=builder)
        runner = ad.create_distributed_session(loss_fn, params, optax.sgd(0.1),
                                               example_batch=batch)
        state = runner.init(params)
        for _ in range(5):
            state, loss = runner.run(state, batch)
        return jax.device_get(state.params), float(loss)

    p_auto, l_auto = run(AutoStrategy())
    p_ar, l_ar = run(AllReduce())
    for k in p_ar:
        np.testing.assert_allclose(p_auto[k], p_ar[k], rtol=1e-6, atol=1e-6)
    assert l_auto == pytest.approx(l_ar, rel=1e-6)


def test_optimizer_flips_regime_on_same_model():
    """Exact state bytes from eval_shape: the SAME model under the SAME budget
    lands in PS/ZeRO with Adam (params + 2x f32 moments), but AllReduce with
    SGD (no state) and Adafactor (factored moments ~ a few % of params)."""
    params = {f"w{i}": np.zeros((512, 512), np.float32) for i in range(3)}
    budget = 7 << 20   # 3 MiB params; Adam needs ~9 MiB, sgd/adafactor ~3 MiB

    def regime(optimizer):
        b = AutoStrategy(memory_budget_bytes=budget, optimizer=optimizer)
        strategy = b.build(ModelSpec(params), _spec())
        return {_which(n) for n in strategy.proto.node_config}

    assert regime(optax.adam(1e-3)) == {"ps_synchronizer"}
    assert regime(optax.sgd(0.1)) == {"all_reduce_synchronizer"}
    assert regime(optax.adafactor(1e-3)) == {"all_reduce_synchronizer"}


def test_session_hands_optimizer_to_builder():
    """create_distributed_session auto-wires observe_optimizer: no manual
    plumbing, the builder sees the session's optimizer."""
    params = {f"w{i}": np.zeros((512, 512), np.float32) for i in range(3)}
    batch = {"x": np.zeros((8, 512), np.float32)}

    def loss(p, b):
        return sum(jnp.sum((b["x"] @ p[k]) ** 2) for k in p)

    for optimizer, want in ((optax.adam(1e-3), "ps_synchronizer"),
                            (optax.sgd(0.1), "all_reduce_synchronizer")):
        builder = AutoStrategy(memory_budget_bytes=7 << 20)
        ad = AutoDist(None, builder)
        ad.create_distributed_session(loss, params, optimizer,
                                      example_batch=batch)
        kinds = {_which(n) for n in ad._strategy.proto.node_config}
        assert kinds == {want}, (kinds, want)


def test_adafactor_recommendation_when_moments_dominate():
    """Memory-bound WITH Adam where params alone fit: the decision log
    recommends factored moments instead of silently sharding."""
    params = {f"w{i}": np.zeros((512, 512), np.float32) for i in range(3)}
    b = AutoStrategy(memory_budget_bytes=7 << 20, optimizer=optax.adam(1e-3))
    b.build(ModelSpec(params), _spec())
    assert "adafactor" in b.explain()


def test_choose_optimizer_picks_by_exact_fit():
    from autodist_tpu.strategy.auto_strategy import choose_optimizer

    params = {"emb": np.zeros((4096, 256), np.float32)}  # 4 MiB
    tight = choose_optimizer(params, memory_budget_bytes=10 << 20)
    roomy = choose_optimizer(params, memory_budget_bytes=64 << 20)
    assert tight.factored and not roomy.factored
    # The chosen optimizers are usable as-is.
    for choice in (tight, roomy):
        state = choice.optimizer.init({"w": jnp.zeros((4, 4))})
        assert state is not None
    assert "exceeds budget" in tight.reason and "<= budget" in roomy.reason


def test_partition_log_prints_exact_bytes(caplog):
    """Threshold comparisons print real byte counts (no '0 MiB >= 0 MiB' at
    scaled-down thresholds)."""
    params = {"big": np.zeros((4096, 64), np.float32)}  # 1 MiB
    b = AutoStrategy(memory_budget_bytes=1 << 30,
                     partition_threshold_bytes=256 << 10)
    b.build(ModelSpec(params), _spec())
    text = b.explain()
    assert "1.00 MiB >= partition threshold 256 KiB" in text, text


def test_explain_has_regime_and_per_param_rows():
    builder = AutoStrategy()
    builder.build(ModelSpec(_dense_params(n=2)), _spec())
    text = builder.explain()
    assert "<regime>" in text and "AllReduce" in text
    assert "w0" in text and "w1" in text
